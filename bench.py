"""Headline benchmark: ResNet-50 training throughput on one chip, measured
through the REAL framework path — Module.bind/init_optimizer +
forward_backward/update/update_metric, i.e. exactly what
``examples/image_classification/train_imagenet.py --benchmark 1`` runs.

Reference equivalent: example/image-classification/train_imagenet.py with
``--benchmark 1`` (synthetic data, common/fit.py:106-116); reference baseline
is 181.53 img/s on 1x P100 (docs/how_to/perf.md:130-139).

The hot loop is ONE fused, donated XLA program per step (Executor.fused_step:
forward + backward + SGD-momentum update; bf16 compute, f32 master params).
Prints ONE JSON line with img/s and MFU.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _peak_flops(backend):
    """Per-chip peak bf16 FLOP/s, for the MFU denominator."""
    if backend == "tpu":
        return 197e12  # TPU v5e: 197 TFLOP/s bf16
    return 0.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--num-steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--skip-attention", action="store_true",
                    help="omit the secondary flash-attention metric")
    ap.add_argument("--skip-transformer", action="store_true",
                    help="omit the model-level transformer-LM metric")
    ap.add_argument("--lm-seq-len", type=int, default=4096)
    ap.add_argument("--lm-hidden", type=int, default=2048)
    ap.add_argument("--lm-layers", type=int, default=6)
    ap.add_argument("--lm-batch", type=int, default=4)
    ap.add_argument("--lm-attn", default="flash",
                    choices=["flash", "splash"],
                    help="attention backend for the LM metric (A/B)")
    cli = ap.parse_args()

    import jax
    import numpy as np

    from examples.image_classification.common import fit
    from examples.image_classification.train_imagenet import get_network

    backend = jax.default_backend()
    batch = cli.batch_size or (256 if backend == "tpu" else 8)
    steps = cli.num_steps if backend == "tpu" else 3
    warmup = cli.warmup if backend == "tpu" else 1

    parser = argparse.ArgumentParser()
    fit.add_fit_args(parser)
    args = parser.parse_args([
        "--network", "resnet-50", "--num-classes", "1000",
        "--image-shape", "3,224,224", "--batch-size", str(batch),
        "--lr", str(cli.lr), "--dtype", cli.dtype, "--benchmark", "1"])
    net = get_network(args)

    stats = fit.benchmark(args, net, num_steps=steps, warmup=warmup)

    if not stats.get("finite", True):
        record = {"metric": "resnet50_train_throughput", "value": 0.0,
                  "unit": "img/s", "vs_baseline": 0.0,
                  "error": "non-finite parameters after training"}
        print(json.dumps(record))
        return record

    img_per_sec = stats["img_per_sec"]
    # ResNet-50 fwd ~= 4.09 GFLOP/img at 224x224; train ~= 3x fwd
    model_flops = 3 * 4.089e9
    peak = _peak_flops(backend)
    mfu = (img_per_sec * model_flops / peak) if peak else None
    record = {
        "metric": "resnet50_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / 181.53, 3),
        "batch_size": batch,
        "dtype": cli.dtype,
        "backend": backend,
        "step_time_ms": round(stats["step_time_ms"], 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "path": "module",
    }
    if backend == "tpu" and not cli.skip_attention:
        # secondary metric: the high-MFU path (flash-attention train step;
        # PERF.md's transformer story). In-process — the TPU is held by
        # this process, a subprocess could not claim it. Never allowed to
        # break the headline.
        try:
            tools_dir = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools")
            if tools_dir not in sys.path:
                sys.path.insert(0, tools_dir)
            from bench_attention import run_bench

            att = run_bench(seq=8192, steps=5)
            record["flash_attention_tflops"] = att["value"]
            record["flash_attention_mfu"] = att["mfu"]
        except Exception as e:
            print("flash-attention secondary bench failed: %r" % (e,),
                  file=sys.stderr)
    if backend == "tpu" and not cli.skip_transformer:
        # first-class MODEL-level metric: transformer-LM train step (seq 4k,
        # bf16, Module fused path) — the framework-level MFU story, not
        # just the attention kernel (examples/transformer/train_lm.py).
        try:
            lm = transformer_lm_bench(seq_len=cli.lm_seq_len,
                                      hidden=cli.lm_hidden,
                                      num_layers=cli.lm_layers,
                                      batch_size=cli.lm_batch,
                                      attn_impl=cli.lm_attn)
            record["transformer_lm_attn"] = cli.lm_attn
            record["transformer_lm_tokens_per_sec"] = round(
                lm["tokens_per_sec"], 1)
            record["transformer_lm_tflops"] = round(lm["model_tflops"], 2)
            record["transformer_lm_mfu"] = round(
                lm["model_tflops"] * 1e12 / _peak_flops(backend), 4)
        except Exception as e:
            print("transformer-LM secondary bench failed: %r" % (e,),
                  file=sys.stderr)
    print(json.dumps(record))
    return record


def transformer_lm_bench(seq_len=4096, hidden=2048, num_layers=6,
                         batch_size=4, num_steps=10, warmup=2,
                         attn_impl="flash"):
    """Model-level transformer-LM train-step benchmark through the Module
    fused path (in-process; the TPU is held by this process).
    ``attn_impl``: "flash" (in-tree kernels) or "splash" (upstream) for
    A/B at the model level."""
    import argparse as _ap

    from examples.transformer import train_lm

    args = train_lm.add_args(_ap.ArgumentParser()).parse_args([
        "--benchmark", "1", "--seq-len", str(seq_len),
        "--hidden", str(hidden), "--num-layers", str(num_layers),
        "--num-heads", str(max(1, hidden // 128)),
        "--batch-size", str(batch_size),
        "--dtype", "bfloat16", "--optimizer", "adam",
        "--num-steps", str(num_steps), "--warmup", str(warmup)])
    import mxnet_tpu as mx

    net = mx.models.get_transformer_lm(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, hidden=args.hidden, seq_len=args.seq_len,
        attn_impl=attn_impl)
    return train_lm.benchmark(args, net)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit the one JSON line even on failure
        print(json.dumps({"metric": "resnet50_train_throughput",
                          "value": 0.0, "unit": "img/s",
                          "vs_baseline": 0.0,
                          "error": "%s: %s" % (type(e).__name__,
                                               str(e)[:300])}))
        sys.exit(1)

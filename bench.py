"""Headline benchmark: ResNet-50 training throughput on one chip.

Reference equivalent: example/image-classification/train_imagenet.py with
``--benchmark 1`` (synthetic data, common/fit.py:106-116); reference baseline
is 181.53 img/s on 1x P100 (docs/how_to/perf.md:130-139).

One fully-jitted train step: forward + backward + SGD-momentum update, mixed
precision (bf16 compute, f32 master params/momentum), donated buffers. Prints
ONE JSON line with img/s and MFU.
"""

import argparse
import json
import sys
import time

import numpy as np


def _peak_flops(backend):
    """Per-chip peak bf16 FLOP/s, for the MFU denominator."""
    if backend == "tpu":
        return 197e12  # TPU v5e: 197 TFLOP/s bf16
    return 0.0


def _init_graph_np(symbol, input_shapes, seed=0):
    """Pure-numpy Xavier init — no device dispatches during setup (each
    imperative init op would round-trip the TPU tunnel)."""
    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = symbol.infer_shape(**input_shapes)
    args = {}
    for name, shape in zip(symbol.list_arguments(), arg_shapes):
        if name in input_shapes:
            continue
        if name.endswith("_bias") or name.endswith("_beta"):
            args[name] = np.zeros(shape, np.float32)
        elif name.endswith("_gamma"):
            args[name] = np.ones(shape, np.float32)
        else:
            fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
            fan_out = shape[0]
            scale = np.sqrt(6.0 / (fan_in + fan_out))
            args[name] = rng.uniform(-scale, scale, shape).astype(np.float32)
    aux = {}
    for name, shape in zip(symbol.list_auxiliary_states(), aux_shapes):
        aux[name] = (np.ones if name.endswith("_var") else
                     np.zeros)(shape, np.float32)
    return args, aux


def build_step(batch, num_classes, lr, momentum, wd, compute_dtype):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import _GraphPlan
    from mxnet_tpu.models import get_resnet

    symbol = get_resnet(num_classes=num_classes, num_layers=50)
    plan = _GraphPlan(symbol)
    args_np, aux_np = _init_graph_np(
        symbol, {"data": (batch, 3, 224, 224), "softmax_label": (batch,)})

    params = {k: jnp.asarray(v) for k, v in args_np.items()}
    moms = {k: jnp.zeros_like(v) for k, v in params.items()}
    aux = {k: jnp.asarray(v) for k, v in aux_np.items()}
    cdt = jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32

    def loss_fn(params, aux, x, y):
        args = {k: v.astype(cdt) for k, v in params.items()}
        args["data"] = x.astype(cdt)
        args["softmax_label"] = y
        (probs,), new_aux = plan.run(args, aux, None, True)
        idx = y.astype(jnp.int32)
        picked = jnp.take_along_axis(
            probs.astype(jnp.float32), idx[:, None], axis=1)[:, 0]
        return -jnp.mean(jnp.log(picked + 1e-8)), new_aux

    def _step(params, moms, aux, x, y):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, aux, x, y)
        new_params, new_moms = {}, {}
        for k in params:
            g = grads[k].astype(jnp.float32) + wd * params[k]
            m = momentum * moms[k] - lr * g
            new_moms[k] = m
            new_params[k] = params[k] + m
        return loss, new_params, new_moms, new_aux

    train_step = jax.jit(_step, donate_argnums=(0, 1, 2))
    return train_step, params, moms, aux


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--num-steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    batch = args.batch_size or (256 if backend == "tpu" else 16)
    steps = args.num_steps if backend == "tpu" else 3
    warmup = args.warmup if backend == "tpu" else 1

    step, params, moms, aux = build_step(
        batch, 1000, args.lr, 0.9, 1e-4, args.dtype)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 3, 224, 224).astype(np.float32))
    y = jnp.asarray((np.arange(batch) % 1000).astype(np.float32))

    for _ in range(warmup):
        loss, params, moms, aux = step(params, moms, aux, x, y)
    float(loss)  # host transfer = hard sync (block_until_ready does not
    # reliably block under the tunneled-device platform)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, moms, aux = step(params, moms, aux, x, y)
    # the final loss depends on every prior step through donated params, so
    # materializing it on host bounds the whole chain
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    if not np.isfinite(loss_val):
        print(json.dumps({"metric": "resnet50_train_throughput", "value": 0.0,
                          "unit": "img/s", "vs_baseline": 0.0,
                          "error": "non-finite loss"}))
        return

    img_per_sec = batch * steps / dt
    # ResNet-50 fwd ~= 4.09 GFLOP/img at 224x224; train ~= 3x fwd
    model_flops = 3 * 4.089e9
    peak = _peak_flops(backend)
    mfu = (img_per_sec * model_flops / peak) if peak else None
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / 181.53, 3),
        "batch_size": batch,
        "dtype": args.dtype,
        "backend": backend,
        "step_time_ms": round(1000 * dt / steps, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "loss": round(loss_val, 4),
    }))


if __name__ == "__main__":
    main()

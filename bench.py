"""Headline benchmark, wedge-resistant two-phase orchestration.

Phase LM (the headline, VERDICT r4 #2): model-level transformer-LM
train-step MFU (seq 4096, bf16, adam) through the REAL framework path —
Module.bind/init_optimizer + forward_backward/update — plus the flash
kernel secondary. Small program, compiles in minutes (and hits the
persistent .jax_cache after the first chip session).

Phase ResNet (the parity track): ResNet-50 training throughput through
the same Module path, i.e. exactly what
``examples/image_classification/train_imagenet.py --benchmark 1`` runs.
Reference equivalent: example/image-classification/train_imagenet.py with
``--benchmark 1`` (synthetic data, common/fit.py:106-116); reference
baseline 181.53 img/s on 1x P100 (docs/how_to/perf.md:130-139). Its
fused fwd+bwd+update program is ~60-90min of cold XLA compile on a
1-core host (minutes once .jax_cache is warm).

Run as ``python bench.py`` each phase executes in its own SUBPROCESS
with a hard timeout — a wedged compile/backend (the BENCH_r04 failure
mode: rc=1, 0.0 img/s, chip unreachable) is killed instead of taking
the whole bench down, and a provisional headline line is printed as
soon as the LM phase lands so even a mid-ResNet kill leaves a parsable
result. The LAST JSON line on stdout is the record of note.

``python bench.py --in-process`` (or ``bench.main()``, used by
tools/tpu_checklist.py which already holds the chip) keeps everything
in one process: a subprocess could not claim the TPU from a parent
that owns it.
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# bench runs always collect step telemetry (MFU/recompile/step-time
# counters); explicit MXNET_TELEMETRY=0 in the environment still wins
os.environ.setdefault("MXNET_TELEMETRY", "1")

# BASELINE.md two-track targets of record (model-level transformer MFU)
LM_ROUND_TARGET = 0.30
LM_NORTH_STAR = 0.40


def _peak_flops(backend):
    """Per-chip peak bf16 FLOP/s, for the MFU denominator."""
    if backend == "tpu":
        return 197e12  # TPU v5e: 197 TFLOP/s bf16
    return 0.0


def _arg_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--num-steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--skip-attention", action="store_true",
                    help="omit the secondary flash-attention metric")
    ap.add_argument("--skip-transformer", action="store_true",
                    help="omit the model-level transformer-LM metric")
    ap.add_argument("--lm-seq-len", type=int, default=4096)
    ap.add_argument("--lm-hidden", type=int, default=2048)
    ap.add_argument("--lm-layers", type=int, default=6)
    ap.add_argument("--lm-batch", type=int, default=4)
    ap.add_argument("--lm-attn", default="flash",
                    choices=["flash", "splash"],
                    help="attention backend for the LM metric (A/B)")
    ap.add_argument("--in-process", action="store_true",
                    help="single-process mode (for callers already "
                         "holding the TPU); default CLI orchestrates "
                         "subprocess phases with hard timeouts")
    ap.add_argument("--phase", choices=["resnet", "lm"], default=None,
                    help="internal: run one phase and print its record")
    ap.add_argument("--resnet-timeout", type=int, default=6600,
                    help="seconds before the ResNet subprocess is killed")
    ap.add_argument("--lm-timeout", type=int, default=2400,
                    help="seconds before the LM subprocess is killed")
    ap.add_argument("--skip-kvstore", action="store_true",
                    help="omit the CPU-only kvstore transport phase")
    ap.add_argument("--kvstore-timeout", type=int, default=240,
                    help="seconds before the kvstore subprocess is killed")
    ap.add_argument("--skip-sparse", action="store_true",
                    help="omit the CPU-only sparse parameter plane phase")
    ap.add_argument("--sparse-timeout", type=int, default=300,
                    help="seconds before the sparse subprocess is killed")
    ap.add_argument("--skip-shard-probe", action="store_true",
                    help="omit the CPU-only GSPMD sharding smoke phase")
    ap.add_argument("--shard-probe-timeout", type=int, default=600,
                    help="seconds before the shard-probe subprocess is "
                         "killed")
    ap.add_argument("--skip-coldstart", action="store_true",
                    help="omit the CPU-only serving cold-start phase")
    ap.add_argument("--coldstart-timeout", type=int, default=300,
                    help="seconds before each cold-start subprocess is "
                         "killed")
    ap.add_argument("--skip-platform", action="store_true",
                    help="skip the CPU-only multi-model platform phase "
                         "(tools/bench_platform.py)")
    ap.add_argument("--platform-timeout", type=int, default=300,
                    help="seconds before the platform phase is killed")
    ap.add_argument("--skip-generate", action="store_true",
                    help="omit the CPU-only continuous-batching "
                         "generation phase")
    ap.add_argument("--generate-timeout", type=int, default=600,
                    help="seconds before the generation subprocess is "
                         "killed")
    return ap


def resnet_bench(cli):
    """ResNet-50 Module-path record (the r1-r4 headline)."""
    import jax

    from examples.image_classification.common import fit
    from examples.image_classification.train_imagenet import get_network

    backend = jax.default_backend()
    batch = cli.batch_size or (256 if backend == "tpu" else 8)
    steps = cli.num_steps if backend == "tpu" else 3
    warmup = cli.warmup if backend == "tpu" else 1

    parser = argparse.ArgumentParser()
    fit.add_fit_args(parser)
    args = parser.parse_args([
        "--network", "resnet-50", "--num-classes", "1000",
        "--image-shape", "3,224,224", "--batch-size", str(batch),
        "--lr", str(cli.lr), "--dtype", cli.dtype, "--benchmark", "1"])
    net = get_network(args)

    stats = fit.benchmark(args, net, num_steps=steps, warmup=warmup)

    if not stats.get("finite", True):
        return {"metric": "resnet50_train_throughput", "value": 0.0,
                "unit": "img/s", "vs_baseline": 0.0,
                "error": "non-finite parameters after training"}

    img_per_sec = stats["img_per_sec"]
    # ResNet-50 fwd ~= 4.09 GFLOP/img at 224x224; train ~= 3x fwd
    model_flops = 3 * 4.089e9
    peak = _peak_flops(backend)
    mfu = (img_per_sec * model_flops / peak) if peak else None
    return {
        "metric": "resnet50_train_throughput",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / 181.53, 3),
        "batch_size": batch,
        "dtype": cli.dtype,
        "backend": backend,
        "step_time_ms": round(stats["step_time_ms"], 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "path": "module",
    }


def _flash_kernel_fields(record):
    """Secondary metric: the flash-attention kernel train step."""
    tools_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from bench_attention import run_bench

    att = run_bench(seq=8192, steps=5, block_q=512, block_k=1024)
    record["flash_attention_tflops"] = att["value"]
    record["flash_attention_mfu"] = att["mfu"]


def _lm_fields(record, cli):
    """First-class MODEL-level metric: transformer-LM train step (seq 4k,
    bf16, Module fused path) — the framework-level MFU story, not just
    the attention kernel (examples/transformer/train_lm.py)."""
    lm = transformer_lm_bench(seq_len=cli.lm_seq_len,
                              hidden=cli.lm_hidden,
                              num_layers=cli.lm_layers,
                              batch_size=cli.lm_batch,
                              attn_impl=cli.lm_attn)
    record["transformer_lm_attn"] = cli.lm_attn
    record["transformer_lm_tokens_per_sec"] = round(
        lm["tokens_per_sec"], 1)
    record["transformer_lm_step_ms"] = round(lm["step_time_ms"], 1)
    record["transformer_lm_tflops"] = round(lm["model_tflops"], 2)
    record["transformer_lm_mfu"] = round(
        lm["model_tflops"] * 1e12 / _peak_flops("tpu"), 4)


def transformer_lm_bench(seq_len=4096, hidden=2048, num_layers=6,
                         batch_size=4, num_steps=10, warmup=2,
                         attn_impl="flash"):
    """Model-level transformer-LM train-step benchmark through the Module
    fused path (in-process; the TPU is held by this process).
    ``attn_impl``: "flash" (in-tree kernels) or "splash" (upstream) for
    A/B at the model level."""
    import argparse as _ap

    from examples.transformer import train_lm

    args = train_lm.add_args(_ap.ArgumentParser()).parse_args([
        "--benchmark", "1", "--seq-len", str(seq_len),
        "--hidden", str(hidden), "--num-layers", str(num_layers),
        "--num-heads", str(max(1, hidden // 128)),
        "--batch-size", str(batch_size),
        "--dtype", "bfloat16", "--optimizer", "adam",
        "--num-steps", str(num_steps), "--warmup", str(warmup)])
    import mxnet_tpu as mx

    net = mx.models.get_transformer_lm(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, hidden=args.hidden, seq_len=args.seq_len,
        attn_impl=attn_impl)
    return train_lm.benchmark(args, net)


def _headline(record):
    """Shape the final one-line JSON. The model-level transformer-LM MFU
    is the headline when measured (BASELINE.md two-track table: model
    >=30% this round, >=40% standing); the ResNet record stays embedded
    (and is the fallback headline when the LM number is absent)."""
    if record.get("transformer_lm_mfu"):
        out = {"metric": "transformer_lm_train_mfu",
               "value": record["transformer_lm_mfu"],
               "unit": "MFU",
               "vs_baseline": round(
                   record["transformer_lm_mfu"] / LM_NORTH_STAR, 3),
               "round_target": LM_ROUND_TARGET,
               "north_star": LM_NORTH_STAR}
        for k, v in record.items():
            if k not in ("metric", "value", "unit", "vs_baseline"):
                out[k] = v
        # keep the parity track visible at the top level
        if record.get("metric") == "resnet50_train_throughput":
            out["resnet50_img_per_sec"] = record.get("value")
            out["resnet50_vs_p100"] = record.get("vs_baseline")
        return out
    return record


def _telemetry_fields(record):
    """Fold the telemetry summary into the record (never allowed to
    break the bench)."""
    try:
        from mxnet_tpu import telemetry
        if telemetry.enabled():
            summ = telemetry.summary()
            if summ:  # nothing ran — keep the record shape unchanged
                record["telemetry"] = summ
    except Exception as e:
        print("telemetry summary failed: %r" % (e,), file=sys.stderr)
    return record


def _autotune_fields(record):
    """Fold the autotuner counters into the record when tuning is on
    (never allowed to break the bench): DB hits prove a fleet-shipped
    tuning DB actually fed this run's configs."""
    try:
        from mxnet_tpu import autotune
        if autotune.enabled():
            record["autotune"] = autotune.stats()
    except Exception as e:
        print("autotune stats failed: %r" % (e,), file=sys.stderr)
    return record


def _guardian_fields(record):
    """Fold the training-guardian counters into the record when the
    guardian is on (never allowed to break the bench): a bench number
    produced alongside skips/rollbacks is not a clean number, and
    anomaly counts on real hardware are the SDC-rate signal."""
    try:
        from mxnet_tpu import guardian
        if guardian.enabled():
            record["guardian"] = guardian.stats()
    except Exception as e:
        print("guardian stats failed: %r" % (e,), file=sys.stderr)
    return record


def main(argv=None):
    """Single-process bench (the pre-r5 behavior): ResNet first, then the
    flash kernel + transformer-LM secondaries. Used by tpu_checklist
    (the chip belongs to that process) and ``--in-process``."""
    cli = _arg_parser().parse_args(argv)

    record = resnet_bench(cli)
    if "error" in record:
        print(json.dumps(record))
        return record
    backend = record.get("backend")
    if backend == "tpu" and not cli.skip_attention:
        # Never allowed to break the headline.
        try:
            _flash_kernel_fields(record)
        except Exception as e:
            print("flash-attention secondary bench failed: %r" % (e,),
                  file=sys.stderr)
    if backend == "tpu" and not cli.skip_transformer:
        try:
            _lm_fields(record, cli)
        except Exception as e:
            print("transformer-LM secondary bench failed: %r" % (e,),
                  file=sys.stderr)
    # keep the resnet-shaped record (metric/value = img/s) — the
    # checklist summarizer scores this shape; only the orchestrated CLI
    # reshapes the headline via _headline()
    _telemetry_fields(record)
    _autotune_fields(record)
    _guardian_fields(record)
    print(json.dumps(record))
    return record


def _phase(cli):
    """Run one phase in THIS process and print its partial record."""
    record = {}
    if cli.phase == "resnet":
        record = resnet_bench(cli)
        # when the lm phase is skipped entirely, the flash kernel
        # secondary still belongs somewhere — run it here
        if (record.get("backend") == "tpu" and cli.skip_transformer
                and not cli.skip_attention and "error" not in record):
            try:
                _flash_kernel_fields(record)
            except Exception as e:
                print("flash kernel secondary failed: %r" % (e,),
                      file=sys.stderr)
    else:
        import mxnet_tpu  # noqa: F401  (applies JAX_PLATFORMS before
        # backend init — the image pins jax_platforms="axon,cpu" and the
        # axon client hangs on a dead tunnel even when cpu is requested)
        import jax

        record["backend"] = jax.default_backend()
        if record["backend"] != "tpu":
            record["lm_skipped"] = "backend %s" % record["backend"]
        else:
            _lm_fields(record, cli)
            if not cli.skip_attention:
                try:
                    _flash_kernel_fields(record)
                except Exception as e:
                    print("flash kernel secondary failed: %r" % (e,),
                          file=sys.stderr)
    _telemetry_fields(record)
    _autotune_fields(record)
    _guardian_fields(record)
    print(json.dumps(record))
    return record


def _run_phase(phase, cli, timeout):
    """Run ``bench.py --phase <phase>`` as a subprocess with a HARD
    timeout (SIGKILL reaches a wedge inside a native XLA call, which an
    in-process SIGALRM cannot — the BENCH_r04 lesson). Returns the
    phase's record dict, or an {"..._error": msg} dict."""
    passthrough = ["--phase", phase,
                   "--num-steps", str(cli.num_steps),
                   "--warmup", str(cli.warmup),
                   "--lr", str(cli.lr), "--dtype", cli.dtype,
                   "--lm-seq-len", str(cli.lm_seq_len),
                   "--lm-hidden", str(cli.lm_hidden),
                   "--lm-layers", str(cli.lm_layers),
                   "--lm-batch", str(cli.lm_batch),
                   "--lm-attn", cli.lm_attn]
    if cli.batch_size:
        passthrough += ["--batch-size", str(cli.batch_size)]
    if cli.skip_attention:
        passthrough += ["--skip-attention"]
    if cli.skip_transformer:
        passthrough += ["--skip-transformer"]
    err_key = "%s_error" % phase
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + passthrough,
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {err_key: "phase killed after %ds (wedged compile or "
                         "unreachable TPU backend)" % timeout}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "error" in rec:
            # normalize any child-side failure (including the __main__
            # fallback JSON, which carries metric/value keys that must
            # not contaminate the merged record) to one error field
            return {err_key: str(rec["error"])[:300]}
        return rec
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {err_key: "rc=%d %s" % (proc.returncode,
                                   "; ".join(tail[-2:])[:300])}


def _kvstore_fields(timeout=240):
    """CPU-only kvstore transport phase (tools/bench_kvstore.py) in a
    subprocess: sync vs async vs async+bucketed push/pull throughput
    over many small keys. Needs no accelerator, so the comm-engine perf
    trajectory gets numbers even when the TPU tunnel is down."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_kvstore.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"kvstore_error":
                "kvstore phase killed after %ds" % timeout}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        return {"kvstore_pushpull_ops_s": rec.get("async_bucket_ops_s"),
                "kvstore_sync_ops_s": rec.get("sync_ops_s"),
                "kvstore_async_ops_s": rec.get("async_ops_s"),
                "kvstore_speedup_async": rec.get("speedup_async"),
                "kvstore_speedup_bucket": rec.get("speedup_bucket")}
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"kvstore_error": "rc=%d %s" % (proc.returncode,
                                           "; ".join(tail[-2:])[:300])}


def _sparse_fields(timeout=300):
    """CPU-only sparse parameter plane phase (tools/bench_sparse.py) in a
    subprocess: touched-rows push+pull over sharded embedding tables vs
    the dense full-table push a sparse-less kvstore would pay each step,
    plus the flat-worker-memory check."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_sparse.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"sparse_error": "sparse phase killed after %ds" % timeout}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        return {"sparse_pushpull_rows_s": rec.get("sparse_rows_s"),
                "sparse_step_ms": rec.get("sparse_step_ms"),
                "sparse_vs_dense_fulltable": rec.get("vs_baseline"),
                "sparse_worker_bytes_flat":
                    rec.get("worker_bytes_flat_vs_table")}
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"sparse_error": "rc=%d %s" % (proc.returncode,
                                          "; ".join(tail[-2:])[:300])}


def _shard_probe_fields(timeout=600):
    """CPU-only GSPMD sharding smoke (tools/shard_probe.py) on a simulated
    8-device mesh: megatron-ruled transformer LM fused step, reporting the
    per-device vs replicated param bytes and the post-SPMD collective mix.
    Needs no accelerator — the sharding subsystem stays continuously
    exercised even when the TPU tunnel is down."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "shard_probe.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=8"))
    try:
        proc = subprocess.run([sys.executable, script, "--smoke"],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"shard_probe_error":
                "shard probe killed after %ds" % timeout}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        return {"shard_mesh": rec.get("mesh"),
                "shard_params_bytes": rec.get("params_sharded_bytes"),
                "shard_replicated_bytes": rec.get("params_replicated_bytes"),
                "shard_collectives": rec.get("collectives")}
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"shard_probe_error": "rc=%d %s" % (proc.returncode,
                                               "; ".join(tail[-2:])[:300])}


def _coldstart_fields(timeout=300):
    """CPU-only serving cold-start phase (tools/bench_coldstart.py):
    time-to-first-prediction for a fresh replica, measured cold (empty
    compile cache: every bucket compiles) and again warm (same cache
    dir: every bucket deserializes).  The warm run must report cache
    hits with zero compiles and a bit-identical first prediction — the
    PR-10 compile-once acceptance measurement, runnable with no
    accelerator."""
    import tempfile

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_coldstart.py")

    def run_once(cache_dir):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MXNET_COMPILE_CACHE_DIR=cache_dir)
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        raise RuntimeError("rc=%d %s" % (proc.returncode,
                                         "; ".join(tail[-2:])[:300]))

    try:
        with tempfile.TemporaryDirectory(prefix="mxtpu-cc-bench-") as d:
            cold = run_once(d)
            warm = run_once(d)
    except (subprocess.TimeoutExpired, RuntimeError, OSError) as e:
        return {"coldstart_error": str(e)[:300]}
    fields = {
        "coldstart_cold_ttfp_ms": cold.get("ttfp_ms"),
        "coldstart_warm_ttfp_ms": warm.get("ttfp_ms"),
        "coldstart_warm_hits": warm.get("cache", {}).get("hits"),
        "coldstart_warm_compiles": warm.get("cache", {}).get("misses"),
        "coldstart_outputs_identical":
            cold.get("out_digest") == warm.get("out_digest"),
    }
    if cold.get("ttfp_ms") and warm.get("ttfp_ms"):
        fields["coldstart_speedup"] = round(
            cold["ttfp_ms"] / warm["ttfp_ms"], 2)
    return fields


def _generate_fields(timeout=600):
    """CPU-only generative-serving phase (tools/bench_generate.py):
    continuous-batching tokens/s under a mixed-length workload vs the
    naive sequential full-prefix re-decode baseline (batch=1, no KV),
    plus TTFT/ITL percentiles, KV-pool peak pages against the
    live-token bound, and the post-warmup decode compile count (zero or
    the shape-static decode loop regressed)."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_generate.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def _mode(extra_args):
        try:
            proc = subprocess.run([sys.executable, script] + extra_args,
                                  capture_output=True, text=True,
                                  timeout=timeout, env=env)
        except (subprocess.TimeoutExpired, OSError) as e:
            return None, str(e)[:300]
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line), None
            except ValueError:
                continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return None, "rc=%d %s" % (proc.returncode,
                                   "; ".join(tail[-2:])[:300])

    fields = {}
    rec, err = _mode([])
    if rec is None:
        fields["generate_error"] = err
    else:
        fields.update({
            "generate_tokens_per_sec": rec.get("value"),
            "generate_naive_tokens_per_sec":
                rec.get("naive_tokens_per_sec"),
            "generate_speedup_vs_naive": rec.get("speedup_vs_naive"),
            "generate_outputs_identical": rec.get("outputs_identical"),
            "generate_ttft_ms_p50": rec.get("ttft_ms_p50"),
            "generate_ttft_ms_p99": rec.get("ttft_ms_p99"),
            "generate_itl_ms_p50": rec.get("itl_ms_p50"),
            "generate_itl_ms_p99": rec.get("itl_ms_p99"),
            "generate_peak_pages": rec.get("peak_pages"),
            "generate_live_token_page_bound":
                rec.get("live_token_page_bound"),
            "generate_cold_decode_runs": rec.get("cold_decode_runs"),
        })
    # prefix-cache phase: TTFT cached vs uncached on a shared-prefix storm
    rec, err = _mode(["--prefix-reuse"])
    if rec is None:
        fields["generate_prefix_error"] = err
    else:
        fields.update({
            "generate_prefix_ttft_reduction": rec.get("value"),
            "generate_prefix_ttft_ms_p50_cached":
                rec.get("ttft_ms_p50_cached"),
            "generate_prefix_ttft_ms_p50_uncached":
                rec.get("ttft_ms_p50_uncached"),
            "generate_prefix_outputs_identical":
                rec.get("outputs_identical"),
            "generate_prefix_hits": rec.get("prefix_hits"),
            "generate_prefix_prefill_tokens_cached":
                rec.get("prefill_tokens_cached"),
        })
    # speculative phase: draft+verify tokens/s vs the plain engine
    rec, err = _mode(["--draft"])
    if rec is None:
        fields["generate_draft_error"] = err
    else:
        fields.update({
            "generate_draft_speedup": rec.get("value"),
            "generate_draft_tokens_per_sec":
                rec.get("tokens_per_sec_draft"),
            "generate_draft_acceptance": rec.get("acceptance"),
            "generate_draft_k": rec.get("draft_k"),
            "generate_draft_outputs_identical":
                rec.get("outputs_identical"),
        })
    return fields


def _platform_fields(timeout=300):
    """CPU-only multi-model platform phase (tools/bench_platform.py) in
    a subprocess: N models on a pool with room for N/2, diurnal demand
    swings driving page-out/fault-in cycles over AOT bundles, plus a
    tenant flood measuring per-tenant shed isolation."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_platform.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except (subprocess.TimeoutExpired, OSError) as e:
        return {"platform_error": str(e)[:300]}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        return {
            "platform_models": rec.get("models"),
            "platform_capacity_models": rec.get("capacity_models"),
            "platform_cold_fault_in_ms": rec.get("cold_fault_in_ms"),
            "platform_warm_fault_in_ms": rec.get("warm_fault_in_ms"),
            "platform_warm_speedup": rec.get("warm_speedup"),
            "platform_fault_ins": rec.get("fault_ins"),
            "platform_page_outs": rec.get("page_outs"),
            "platform_warm_cold_bucket_runs":
                rec.get("warm_cold_bucket_runs"),
            "platform_tenant_p99_ms": rec.get("tenant_p99_ms"),
            "platform_noisy_shed": rec.get("noisy_shed"),
            "platform_good_shed": rec.get("good_shed"),
        }
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"platform_error": "rc=%d %s" % (proc.returncode,
                                            "; ".join(tail[-2:])[:300])}


def _probe_backend(timeout=300):
    """Claim and release the backend in a subprocess. Returns None when
    healthy, else a short error string."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import mxnet_tpu, jax; d = jax.devices();"
             "x = jax.numpy.ones((8, 8)); (x @ x).block_until_ready();"
             "print('probe-ok', d)"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if "probe-ok" not in probe.stdout:
            out = (probe.stderr or probe.stdout).strip()
            raise RuntimeError(out.splitlines()[-1][:200] if out
                               else "no output")
    except (subprocess.TimeoutExpired, RuntimeError) as e:
        return ("backend probe failed (unreachable TPU tunnel?): %s"
                % (e,))[:300]
    return None


def orchestrate(argv=None):
    """Default CLI path: LM phase first (fast; provisional headline line
    printed immediately), then the ResNet phase, then the merged record.
    The driver parses the LAST JSON line, so a kill at any point after
    the LM phase still leaves a scored result."""
    cli = _arg_parser().parse_args(argv)
    record = {}

    # cheap liveness probe: a dead/wedged TPU tunnel (the BENCH_r04
    # failure mode) should cost 5 minutes, not the sum of both phase
    # timeouts. The probe claims and releases the chip before phase 1.
    def error_record(msg):
        return {"metric": "transformer_lm_train_mfu", "value": 0.0,
                "unit": "MFU", "vs_baseline": 0.0, "error": msg[:300]}

    # CPU-only phases FIRST: they need no accelerator, so their numbers
    # survive every early return below (dead tunnel included)
    kv_fields = {} if cli.skip_kvstore else \
        _kvstore_fields(cli.kvstore_timeout)
    sparse_fields = {} if cli.skip_sparse else \
        _sparse_fields(cli.sparse_timeout)
    shard_fields = {} if cli.skip_shard_probe else \
        _shard_probe_fields(cli.shard_probe_timeout)
    coldstart_fields = {} if cli.skip_coldstart else \
        _coldstart_fields(cli.coldstart_timeout)
    generate_fields = {} if cli.skip_generate else \
        _generate_fields(cli.generate_timeout)
    platform_fields = {} if cli.skip_platform else \
        _platform_fields(cli.platform_timeout)

    def finish(rec):
        rec.update(kv_fields)
        rec.update(sparse_fields)
        rec.update(shard_fields)
        rec.update(coldstart_fields)
        rec.update(generate_fields)
        rec.update(platform_fields)
        print(json.dumps(rec))
        return rec

    err = _probe_backend()
    if err:
        return finish(error_record(err))

    if not cli.skip_transformer:
        record.update(_run_phase("lm", cli, cli.lm_timeout))
        if record.get("transformer_lm_mfu"):
            print(json.dumps(_headline(dict(record))), flush=True)
        # the tunnel flaps mid-session (PERF.md round-5 timeline):
        # re-probe before committing to the long ResNet phase, whether
        # the LM phase succeeded or died
        if _probe_backend():
            if record.get("transformer_lm_mfu"):
                record = _headline(record)
                record["resnet_error"] = \
                    "tunnel died after the LM phase; ResNet skipped"
            else:
                record = error_record(
                    "tunnel died during the LM phase: %s"
                    % record.get("lm_error"))
            return finish(record)

    resnet = _run_phase("resnet", cli, cli.resnet_timeout)
    metric_fields = {k: resnet.pop(k, None) for k in
                     ("metric", "value", "unit", "vs_baseline")}
    record.update({k: v for k, v in resnet.items() if v is not None})
    if metric_fields.get("metric"):
        record.update({k: v for k, v in metric_fields.items()
                       if v is not None})

    record = _headline(record)
    if "value" not in record:  # both phases failed
        record = {"metric": "transformer_lm_train_mfu", "value": 0.0,
                  "unit": "MFU", "vs_baseline": 0.0,
                  "error": "; ".join(str(record[k]) for k in record
                                     if k.endswith("_error"))[:300]}
    return finish(record)


if __name__ == "__main__":
    try:
        if "--phase" in sys.argv:
            _phase(_arg_parser().parse_args())
        elif "--in-process" in sys.argv:
            main()
        else:
            rec = orchestrate()
            if "error" in rec:
                sys.exit(1)
    except Exception as e:  # emit the one JSON line even on failure
        print(json.dumps({"metric": "transformer_lm_train_mfu",
                          "value": 0.0, "unit": "MFU",
                          "vs_baseline": 0.0,
                          "error": "%s: %s" % (type(e).__name__,
                                               str(e)[:300])}))
        sys.exit(1)

"""KVStore — parameter synchronization facade.

TPU-native redesign of /root/reference/src/kvstore/ + python/mxnet/kvstore.py.
The reference moves gradients through Comm (pinned-host or GPU-P2P reduce)
and ps-lite; on TPU the synchronous data-parallel path is XLA collectives
(``psum`` over a mesh axis) compiled *into* the training step, so ``local``
and ``device`` collapse to the same thing: an aggregation point that applies
the optimizer once per key.  The KVStore class keeps the reference's API
(init/push/pull/set_optimizer/rank/num_workers) so Module and user scripts
port unchanged; multi-host ``dist_*`` flavors ride ``jax.distributed`` +
the global mesh (parallel/ package) rather than a parameter server.

Push semantics match kvstore_local.h:50-95: pushed grads for one key are
summed; with an updater installed the update runs eagerly on push and pull
returns the stored weight; without one, pull returns the summed grads.
"""
from __future__ import annotations

import logging
import os
import pickle
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from .base import MXNetError, register_env
from .ndarray import NDArray
from . import ndarray as nd
from . import optimizer as opt
__all__ = ["KVStore", "create", "install_preemption_handler",
           "NonFiniteGradientError"]


def __getattr__(name):
    # typed NACK for non-finite pushes (numeric containment) — re-exported
    # here because workers catch it around push(), not around server code.
    # Lazy: an eager import would run kvstore_server's DMLC_ROLE=server
    # bootstrap earlier than the package __init__ sequences it.
    if name == "NonFiniteGradientError":
        from .kvstore_server import NonFiniteGradientError

        return NonFiniteGradientError
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

register_env("MXNET_KVSTORE_COMPRESS", "", str,
             "Wire compression for dist_async push payloads: 'fp16' halves "
             "gradient bytes with per-key error-feedback residuals "
             "(convergence-preserving); empty disables.")
register_env("MXNET_KVSTORE_ELASTIC", 0, int,
             "Elastic membership for dist_async: workers join the server's "
             "live-rank table, barriers and sync rounds size themselves by "
             "the current generation, and a preemption handler is installed "
             "on the Module path (fault_tolerance.md §elasticity).")
register_env("MXNET_KVSTORE_ELASTIC_JOIN", 0, int,
             "Set by launch.py --elastic on respawned workers: this process "
             "is a mid-run joiner — it rides the recovery bring-up (skip "
             "startup barriers, pull current params) and aligns with the "
             "fleet at the next barrier.")
register_env("MXNET_KVSTORE_DRAIN_TIMEOUT", 30, float,
             "Seconds the preemption handler waits for in-flight comm-engine "
             "ops to drain before checkpointing and leaving.")


def _key_list(key):
    return (key if isinstance(key, (list, tuple)) else [key]), \
        not isinstance(key, (list, tuple))


def _val_list(value, nkeys):
    if isinstance(value, (list, tuple)) and nkeys == 1 and \
            not isinstance(value[0], (list, tuple)):
        return [list(value)]
    if nkeys == 1:
        return [value if isinstance(value, list) else [value]]
    out = []
    for v in value:
        out.append(v if isinstance(v, list) else [v])
    return out


class KVStore:
    """Single-process key-value store (reference kvstore.h:26-286 'local' /
    'device')."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store: Dict[Union[int, str], NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        import jax

        if "dist" in self._type:
            return jax.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        import jax

        if "dist" in self._type:
            return jax.process_count()
        return 1

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            self._store[k] = v[0].copy() if isinstance(v[0], NDArray) \
                else nd.array(v[0])

    def push(self, key, value, priority=0):
        """Sum pushed values per key; run the updater eagerly if installed
        (reference KVStoreLocal::Push, kvstore_local.h:50)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("push to uninitialized key %s" % str(k))
            merged = vlist[0]
            if len(vlist) > 1:
                acc = vlist[0]._data
                for v in vlist[1:]:
                    acc = acc + v._data
                merged = NDArray(acc, vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # no updater: the store holds the merged sum of this push
                # (reference KVStoreLocal::Push CopyFromTo(merged, &local))
                self._store[k]._set(merged._data)

    def pull(self, key, out=None, priority=0):
        keys, single = _key_list(key)
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull of uninitialized key %s" % str(k))
            src = self._store[k]
            for o in olist:
                data = src._data.astype(o.dtype) if o.dtype != src.dtype \
                    else src._data
                # keep the destination's placement: pulling into a
                # mesh-replicated parameter must not collapse it onto the
                # store's single device
                if getattr(o._data, "sharding", None) is not None and \
                        data.sharding != o._data.sharding:
                    import jax

                    data = jax.device_put(data, o._data.sharding)
                o._set(data)

    # -- synchronization ---------------------------------------------------
    def wait(self, keys=None):
        """Block until outstanding ops on ``keys`` (all, when None) have
        completed.  Synchronous flavors finish every push/pull before
        returning, so this is a no-op; the async facade
        (comm_engine.AsyncKVStore) overrides it with a real barrier."""

    def wait_all(self):
        """Block until every outstanding op has completed (no-op here;
        see ``wait``)."""

    def drain(self, timeout=None):
        """Finish outstanding async work before a preemption exit (no-op
        for synchronous stores; the comm-engine facade overrides this
        with a bounded wait).  Returns True once everything completed."""
        return True

    # -- control plane -----------------------------------------------------
    def set_optimizer(self, optimizer):
        """Install an optimizer as the store-side updater.  In dist mode the
        reference pickles it to the servers (kvstore.py:232-255); collective
        DP needs no server, so both paths install locally."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())


class DistAsyncKVStore(KVStore):
    """``dist_async`` over the host-side parameter service
    (kvstore_server.py): every push triggers the server updater immediately
    — no worker synchronization (reference kvstore_dist_server.h:198-206
    async branch + kvstore_dist.h worker client)."""

    def __init__(self, kv_type="dist_async"):
        import os

        super().__init__(kv_type)
        from . import kvstore_server as kvs

        host = os.environ.get("DMLC_PS_ROOT_URI")
        # DMLC_SERVER_URIS ("h1:p1,h2:p2") is the launcher's authoritative
        # server list and stands on its own — no root URI needed (the
        # sparse-plane tests point a worker at already-running servers
        # this way)
        uris = os.environ.get("DMLC_SERVER_URIS")
        if host or uris:
            port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
            self._server = None
            # multi-server fleet: DMLC_SERVER_URIS when servers live on
            # different hosts, else root_port+i on the root host (the
            # launcher starts DMLC_NUM_SERVER of them)
            if uris:
                addrs = [(h, int(p)) for h, p in
                         (u.rsplit(":", 1) for u in uris.split(","))]
            else:
                n_srv = max(1, int(os.environ.get("DMLC_NUM_SERVER",
                                                  "1") or "1"))
                addrs = [(host, port + i) for i in range(n_srv)]
        else:
            # single-process bring-up: run the service in-process so the
            # async path works without a launcher
            self._server = kvs.start_server(
                num_workers=int(os.environ.get("DMLC_NUM_WORKER", "1")))
            addrs = [self._server.addr]
        self._clients = [kvs.ServerClient(h, p) for h, p in addrs]
        self._client = self._clients[0]
        # reference kvstore_dist.h:264-302: arrays with at least this many
        # elements are range-split evenly across the server fleet
        self._bigarray_bound = int(
            os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", str(1000 * 1000)))
        self._rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        # rejoin semantics (reference kvstore_dist.h:35-38 IsRecovery):
        # a relaunched worker must NOT wait at startup barriers — its
        # peers are mid-training and will never arrive. Server state is
        # safe: init is setdefault on the server, so re-init cannot
        # clobber trained weights; the worker pulls current ones. The
        # flag covers ONLY the bring-up phase: it expires at the first
        # push (bring-up itself pulls — Module interleaves init/pull per
        # parameter), so later barriers participate normally and a later
        # legitimate set_optimizer (LR drop at an epoch boundary)
        # installs instead of being dropped as a recovery re-ship.
        self._is_recovery = (
            os.environ.get("DMLC_IS_RECOVERY", "") == "1"
            or int(os.environ.get("MXNET_AUTORESUME_ATTEMPT", "0") or 0) > 0)
        self._pool = None  # lazy; lives for the store's lifetime
        # optional fp16 wire compression with error feedback: the
        # quantization error of each push is carried into the next one
        # per key, so the server integrates the true gradient sum over
        # time (convergence-preserving, unlike plain truncation)
        comp = os.environ.get("MXNET_KVSTORE_COMPRESS", "").lower()
        if comp in ("none", "0"):
            comp = ""
        if comp not in ("", "fp16"):
            raise MXNetError(
                "unsupported MXNET_KVSTORE_COMPRESS %r (only 'fp16')"
                % comp)
        self._compress = comp
        self._residuals: Dict[object, np.ndarray] = {}
        # elastic membership (docs/how_to/fault_tolerance.md §elasticity):
        # join every server's live-rank table so barriers and sync rounds
        # size themselves by the current generation.  A mid-run joiner
        # (MXNET_KVSTORE_ELASTIC_JOIN, set by launch.py --elastic on
        # respawns) additionally rides the recovery bring-up so it pulls
        # current params and aligns at the NEXT barrier instead of
        # waiting at startup ones.
        self._elastic = os.environ.get("MXNET_KVSTORE_ELASTIC", "0") == "1"
        self._left = False
        if os.environ.get("MXNET_KVSTORE_ELASTIC_JOIN", "0") == "1":
            self._is_recovery = True
        # liveness: periodic heartbeat so the server can report dead peers
        # and release stuck barriers (kvstore_dist.h:151-160 parity)
        hb_interval = float(os.environ.get(
            "MXNET_KVSTORE_HEARTBEAT_INTERVAL", "5"))
        if self._elastic:
            for c in self._clients:
                c.join(self._rank)
                # heartbeat EVERY server: each keeps its own eviction
                # clock, and a beat to server 0 alone would get this rank
                # evicted from the rest of the fleet
                c.start_heartbeat(self._rank, interval=hb_interval)
        else:
            self._client.start_heartbeat(self._rank, interval=hb_interval)

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._num_workers

    # -- key placement (reference kvstore_dist.h:264-302) -----------------
    def _server_for(self, key):
        """Stable small-key placement (crc32, NOT hash(): the builtin is
        salted per process, so workers would disagree)."""
        import zlib

        return zlib.crc32(str(key).encode()) % len(self._clients)

    def _ranges(self, n):
        """Even contiguous [lo, hi) element ranges, one per server."""
        ns = len(self._clients)
        base, rem = divmod(n, ns)
        bounds = [0]
        for i in range(ns):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        return list(zip(bounds[:-1], bounds[1:]))

    def _is_sharded(self, n_elements):
        return (len(self._clients) > 1
                and n_elements >= self._bigarray_bound)

    def _client_pool(self):
        """One long-lived thread pool for concurrent per-server RPCs —
        push/pull run every step; spawning threads per call would sit on
        the training hot path."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(len(self._clients))
        return self._pool

    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, v in zip(keys, vals):
            if self._rank == 0:
                arr = v[0].asnumpy() if isinstance(v[0], NDArray) else \
                    np.asarray(v[0])
                if self._is_sharded(arr.size):
                    flat = arr.reshape(-1)
                    for cid, (lo, hi) in enumerate(self._ranges(arr.size)):
                        self._clients[cid].init(k, flat[lo:hi])
                else:
                    self._clients[self._server_for(k)].init(k, arr)
        # the server decides whether a recovered worker may skip (only
        # once the job passed startup — see KVStoreServer barrier); the
        # init sends above are setdefault-safe either way
        self._client.barrier(rank=self._rank,
                             is_recovery=self._is_recovery)

    @staticmethod
    def _merge_vals(vlist):
        """Sum a key's device values ON DEVICE, then transfer the result
        to host once (the old path round-tripped every value through
        asnumpy() before summing — num_device host transfers per key)."""
        if not isinstance(vlist[0], NDArray):
            merged = np.asarray(vlist[0])
            for v in vlist[1:]:
                merged = merged + np.asarray(v)
            return merged
        if len(vlist) == 1:
            return vlist[0].asnumpy()
        acc = vlist[0]._data
        for v in vlist[1:]:
            acc = acc + v._data
        return NDArray(acc, vlist[0].context).asnumpy()

    def _compress_out(self, rkey, arr):
        """fp16 wire compression with error feedback: residual r_{t} =
        (g_t + r_{t-1}) - fp16(g_t + r_{t-1}) is replayed into the next
        push of the same key, so quantization error never accumulates."""
        if self._compress != "fp16" or arr.dtype.kind != "f" \
                or arr.dtype == np.float16:
            return arr
        prev = self._residuals.get(rkey)
        acc = arr + prev if prev is not None else arr
        sent = acc.astype(np.float16)
        self._residuals[rkey] = acc - sent.astype(arr.dtype)
        return sent

    def push(self, key, value, priority=0):
        self._is_recovery = False  # training traffic: bring-up is over
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            self._push_one(k, self._merge_vals(vlist))

    def _push_one(self, k, merged):
        if self._is_sharded(merged.size):
            flat = merged.reshape(-1)
            # residuals are tracked per (key, range-start): each server
            # sees a consistent error-feedback stream for its shard
            parts = [(cid, self._compress_out((k, lo), flat[lo:hi]))
                     for cid, (lo, hi) in
                     enumerate(self._ranges(merged.size))]
            list(self._client_pool().map(
                lambda p: self._clients[p[0]].push(k, p[1],
                                                   rank=self._rank),
                parts))
        else:
            self._clients[self._server_for(k)].push(
                k, self._compress_out(k, merged), rank=self._rank)

    def push_multi(self, pairs):
        """Fused push of many ``(key, vlist)`` pairs: merge + compress per
        key, group by owning server, then ONE batched ``multi`` RPC per
        server (concurrent across the fleet).  The transport's
        per-envelope idempotency token covers the whole bucket, so
        crash-replay applies it exactly once."""
        self._is_recovery = False
        groups: Dict[int, list] = {}
        big = []
        for k, vlist in pairs:
            merged = self._merge_vals(vlist)
            if self._is_sharded(merged.size):
                big.append((k, merged))  # range-split path, key at a time
                continue
            groups.setdefault(self._server_for(k), []).append(
                ("push", k, self._compress_out(k, merged), self._rank))
        items = list(groups.items())
        if len(items) == 1:
            self._clients[items[0][0]].multi(items[0][1])
        elif items:
            list(self._client_pool().map(
                lambda it: self._clients[it[0]].multi(it[1]), items))
        for k, merged in big:
            self._push_one(k, merged)

    @staticmethod
    def _write_out(arr, olist):
        """Write a pulled host array into the destination NDArrays (dtype
        cast + destination-sharding preservation, see KVStore.pull)."""
        import jax

        for o in olist:
            data = nd.array(arr, dtype=o.dtype)._data
            if getattr(o._data, "sharding", None) is not None and \
                    data.sharding != o._data.sharding:
                data = jax.device_put(data, o._data.sharding)
            o._set(data)

    def pull(self, key, out=None, priority=0):
        # NOTE: pull must NOT clear _is_recovery — Module bring-up
        # interleaves init/pull per parameter (model.py
        # _initialize_kvstore) before set_optimizer ever runs; only push
        # marks real training traffic.
        keys, _ = _key_list(key)
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            want = olist[0]
            if self._is_sharded(int(np.prod(want.shape))):
                # concurrent per-server pulls: latency is max-of-servers,
                # not sum (the point of the range split; the reference's
                # ps-lite worker overlaps its range requests the same way)
                parts = list(self._client_pool().map(
                    lambda c: c.pull(k), self._clients))
                arr = np.concatenate(
                    [np.asarray(p).reshape(-1) for p in parts]
                ).reshape(want.shape)
            else:
                arr = self._clients[self._server_for(k)].pull(k)
            self._write_out(arr, olist)

    def pull_multi(self, pairs):
        """Fused pull of many ``(key, olist)`` pairs: group by owning
        server, one batched ``multi`` RPC per server (concurrent across
        the fleet), then write destinations."""
        small, big = [], []
        for k, olist in pairs:
            if self._is_sharded(int(np.prod(olist[0].shape))):
                big.append((k, olist))
            else:
                small.append((k, olist))
        groups: Dict[int, list] = {}
        for i, (k, _) in enumerate(small):
            groups.setdefault(self._server_for(k), []).append(i)
        def fetch(item):
            cid, idxs = item
            replies = self._clients[cid].multi(
                [("pull", small[i][0]) for i in idxs])
            return list(zip(idxs, replies))
        items = list(groups.items())
        if len(items) == 1:
            results = fetch(items[0])
        elif items:
            results = [r for rs in self._client_pool().map(fetch, items)
                       for r in rs]
        else:
            results = []
        # one fused host→device transfer for the whole group: a
        # device_put dispatch per key is the measured bottleneck at
        # many-small-key scale, not the wire
        import jax

        hosts, dests = [], []
        for i, arr in results:
            arr = np.asarray(arr)
            for o in small[i][1]:
                hosts.append(arr if arr.dtype == o.dtype
                             else arr.astype(o.dtype))
                dests.append(o)
        for o, data in zip(dests, self._to_device(hosts)):
            if getattr(o._data, "sharding", None) is not None and \
                    data.sharding != o._data.sharding:
                data = jax.device_put(data, o._data.sharding)
            o._set(data)
        for k, olist in big:
            self.pull(k, olist)

    @staticmethod
    def _to_device(hosts):
        """Move a group of host arrays to device with ONE transfer:
        concatenate flat, one device_put, split on device.  Per-array
        device_put (even jax's batched form) costs ~25-40us of dispatch
        per key; the fused path amortizes it across the group."""
        import jax
        import jax.numpy as jnp

        if not hosts:
            return []
        dt = hosts[0].dtype
        if len(hosts) == 1 or any(h.dtype != dt for h in hosts):
            return jax.device_put(hosts)
        flats = [h.reshape(-1) for h in hosts]
        big = jax.device_put(np.concatenate(flats))
        offs = np.cumsum([f.size for f in flats])[:-1].tolist()
        return [p if p.shape == h.shape else p.reshape(h.shape)
                for p, h in zip(jnp.split(big, offs), hosts)]

    def get_num_dead_node(self, node_id=0, timeout=None):
        """Count workers whose heartbeat went stale (reference
        kvstore.get_num_dead_node over ps::Postoffice::GetDeadNodes,
        kvstore_dist.h:151-160).  ``timeout=None`` uses the server's own
        ``MXNET_KVSTORE_HEARTBEAT_TIMEOUT`` default, so callers and the
        barrier dead-peer release agree on who is dead."""
        try:
            return len(self._client.dead_nodes(
                None if timeout is None else float(timeout)))
        except Exception:
            # server unreachable: from this worker's view the service
            # itself is dead
            return 1

    # -- elastic membership -------------------------------------------------
    def membership(self):
        """Live membership view ``{gen, ranks, num_workers}``."""
        return self._client.membership()

    def leave(self):
        """Graceful preemption exit: drop this rank from every server's
        live set so the survivors' barriers and merge rounds re-form
        immediately.  Idempotent; failures are logged, not raised — a
        leaving worker cannot do anything about a dead server."""
        if self._left:
            return
        self._left = True
        for c in self._clients:
            try:
                c.leave(self._rank)
            except Exception as e:
                logging.warning("kvstore leave(rank=%d) failed: %s",
                                self._rank, e)

    def close(self):
        """Tear down the client sockets and any in-process server."""
        try:
            if self._elastic:
                self.leave()
            if self._pool is not None:
                self._pool.shutdown(wait=False)
                self._pool = None
            for c in self._clients:
                c.close()
        finally:
            if self._server is not None:
                self._server.stop()
                self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def set_optimizer(self, optimizer):
        """Ship the pickled optimizer to every server (reference
        kvstore.py:232-255 _send_command_to_servers)."""
        if self._rank == 0:
            # recovery flag travels with the command: the server keeps
            # its live updater (momentum state) when one is installed
            for c in self._clients:
                c.set_optimizer(optimizer, is_recovery=self._is_recovery)
        self._client.barrier(rank=self._rank,
                             is_recovery=self._is_recovery)

    def _barrier(self):
        self._client.barrier(rank=self._rank,
                             is_recovery=self._is_recovery)

    def _send_command_to_servers(self, head, body):
        if head == "stop":
            for c in self._clients:
                c.stop_server()

    def save_optimizer_states(self, fname):
        raise MXNetError("Cannot save states for distributed training")

    def load_optimizer_states(self, fname):
        raise MXNetError("Cannot load states for distributed training")


def install_preemption_handler(kv, checkpoint_fn=None, sig=None,
                               drain_timeout=None, exit_process=True):
    """Install the elastic preemption path on ``sig`` (default SIGTERM):
    drain in-flight comm-engine ops (bounded by
    ``MXNET_KVSTORE_DRAIN_TIMEOUT``), run ``checkpoint_fn`` if given,
    send the ``leave`` RPC so the surviving fleet re-forms immediately,
    and exit 0 — a clean preemption must not look like a crash to
    ``launch.py`` auto-resume.  Returns the handler (tests invoke it
    directly); the signal itself is only hooked from the main thread
    (``signal.signal`` constraint — elsewhere the handler comes back
    uninstalled)."""
    import signal as _signal
    import threading

    if sig is None:
        sig = _signal.SIGTERM
    if drain_timeout is None:
        drain_timeout = float(os.environ.get(
            "MXNET_KVSTORE_DRAIN_TIMEOUT", "30"))
    fired = threading.Event()

    def handler(signum=None, frame=None):
        if fired.is_set():
            return
        fired.set()
        logging.info("preemption signal: draining comm ops "
                     "(%.0fs budget), checkpointing, leaving", drain_timeout)
        try:
            kv.drain(drain_timeout)
        except Exception as e:
            logging.warning("preemption drain failed: %s", e)
        if checkpoint_fn is not None:
            try:
                checkpoint_fn()
            except Exception as e:
                logging.warning("preemption checkpoint failed: %s", e)
        leave = getattr(kv, "leave", None)
        if leave is not None:
            try:
                leave()
            except Exception as e:
                logging.warning("preemption leave failed: %s", e)
        try:
            # flight recorder: the postmortem is the only record of this
            # process's final state once we _exit (no atexit hooks run)
            from . import telemetry as _tm

            _tm.flight_recorder.dump("preemption-sigterm")
        except Exception:
            pass
        if exit_process:
            os._exit(0)

    if threading.current_thread() is threading.main_thread():
        try:
            _signal.signal(sig, handler)
        except (ValueError, OSError):
            pass
    return handler


def create(name="local") -> KVStore:
    """Create a KVStore (reference KVStore::Create, kvstore.cc:17-45).
    'local'/'device' → in-process aggregation (XLA fuses the reduce);
    'dist_sync'/'dist_device_sync' → multi-host SPMD where sync semantics
    come from in-step collectives (jax.distributed + global mesh), so no
    server round-trips; 'dist_async' → the host-side parameter service
    (kvstore_server.py), updater applied on every push."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name not in ("local", "local_update_cpu", "local_allreduce_cpu",
                    "local_allreduce_device", "device", "dist_sync",
                    "dist_device_sync", "dist_async", "dist"):
        raise MXNetError("unknown KVStore type %s" % name)
    if name == "dist_async":
        return DistAsyncKVStore(name)
    if name in ("dist_sync", "dist_device_sync", "dist"):
        from .kvstore_dist import DistSyncKVStore

        return DistSyncKVStore(name)
    return KVStore(name)

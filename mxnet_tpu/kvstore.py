"""KVStore — parameter synchronization facade.

TPU-native redesign of /root/reference/src/kvstore/ + python/mxnet/kvstore.py.
The reference moves gradients through Comm (pinned-host or GPU-P2P reduce)
and ps-lite; on TPU the synchronous data-parallel path is XLA collectives
(``psum`` over a mesh axis) compiled *into* the training step, so ``local``
and ``device`` collapse to the same thing: an aggregation point that applies
the optimizer once per key.  The KVStore class keeps the reference's API
(init/push/pull/set_optimizer/rank/num_workers) so Module and user scripts
port unchanged; multi-host ``dist_*`` flavors ride ``jax.distributed`` +
the global mesh (parallel/ package) rather than a parameter server.

Push semantics match kvstore_local.h:50-95: pushed grads for one key are
summed; with an updater installed the update runs eagerly on push and pull
returns the stored weight; without one, pull returns the summed grads.
"""
from __future__ import annotations

import pickle
from typing import Callable, Dict, List, Optional, Union

from .base import MXNetError
from .ndarray import NDArray
from . import ndarray as nd
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key_list(key):
    return (key if isinstance(key, (list, tuple)) else [key]), \
        not isinstance(key, (list, tuple))


def _val_list(value, nkeys):
    if isinstance(value, (list, tuple)) and nkeys == 1 and \
            not isinstance(value[0], (list, tuple)):
        return [list(value)]
    if nkeys == 1:
        return [value if isinstance(value, list) else [value]]
    out = []
    for v in value:
        out.append(v if isinstance(v, list) else [v])
    return out


class KVStore:
    """Single-process key-value store (reference kvstore.h:26-286 'local' /
    'device')."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store: Dict[Union[int, str], NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        import jax

        if "dist" in self._type:
            return jax.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        import jax

        if "dist" in self._type:
            return jax.process_count()
        return 1

    # -- data plane --------------------------------------------------------
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            self._store[k] = v[0].copy() if isinstance(v[0], NDArray) \
                else nd.array(v[0])

    def push(self, key, value, priority=0):
        """Sum pushed values per key; run the updater eagerly if installed
        (reference KVStoreLocal::Push, kvstore_local.h:50)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("push to uninitialized key %s" % str(k))
            merged = vlist[0]
            if len(vlist) > 1:
                acc = vlist[0]._data
                for v in vlist[1:]:
                    acc = acc + v._data
                merged = NDArray(acc, vlist[0].context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # no updater: the store holds the merged sum of this push
                # (reference KVStoreLocal::Push CopyFromTo(merged, &local))
                self._store[k]._set(merged._data)

    def pull(self, key, out=None, priority=0):
        keys, single = _key_list(key)
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull of uninitialized key %s" % str(k))
            src = self._store[k]
            for o in olist:
                data = src._data.astype(o.dtype) if o.dtype != src.dtype \
                    else src._data
                # keep the destination's placement: pulling into a
                # mesh-replicated parameter must not collapse it onto the
                # store's single device
                if getattr(o._data, "sharding", None) is not None and \
                        data.sharding != o._data.sharding:
                    import jax

                    data = jax.device_put(data, o._data.sharding)
                o._set(data)

    # -- control plane -----------------------------------------------------
    def set_optimizer(self, optimizer):
        """Install an optimizer as the store-side updater.  In dist mode the
        reference pickles it to the servers (kvstore.py:232-255); collective
        DP needs no server, so both paths install locally."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())


def create(name="local") -> KVStore:
    """Create a KVStore (reference KVStore::Create, kvstore.cc:17-45).
    'local'/'device' → in-process aggregation (XLA fuses the reduce);
    'dist_sync'/'dist_device_sync'/'dist_async' → same API over
    jax.distributed (multi-host SPMD: sync semantics come from in-step
    collectives, so dist_sync needs no server round-trips)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name not in ("local", "local_update_cpu", "local_allreduce_cpu",
                    "local_allreduce_device", "device", "dist_sync",
                    "dist_device_sync", "dist_async", "dist"):
        raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)

"""Network visualization (parity: /root/reference/python/mxnet/visualization.py):
``print_summary`` table and ``plot_network`` graphviz dot output."""
from __future__ import annotations

import json
from typing import Dict, Optional

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a per-layer summary table with output shapes and param counts
    (reference visualization.py print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {x[0] for x in conf["heads"]}
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
                    if show_shape:
                        key = input_name + "_output" if input_node["op"] != "null" \
                            else input_name
                        if key in shape_dict:
                            shape = shape_dict[key][1:]
                            pre_filter = pre_filter + int(shape[0]) if shape else 0
        cur_param = 0
        attrs = node.get("attr", {}) or {}
        if op == "Convolution":
            num_filter = int(attrs["num_filter"])
            kernel = eval(attrs["kernel"])
            num_group = int(attrs.get("num_group", "1"))
            cur_param = pre_filter * num_filter // num_group
            for k in kernel:
                cur_param *= k
            cur_param += num_filter
        elif op == "FullyConnected":
            num_hidden = int(attrs["num_hidden"])
            cur_param = (pre_filter + 1) * num_hidden
        elif op == "BatchNorm":
            key = node["name"] + "_output"
            if show_shape and key in shape_dict:
                num_filter = shape_dict[key][1]
                cur_param = int(num_filter) * 2
        if not pre_node:
            first_connection = ""
        else:
            first_connection = pre_node[0]
        fields = [node["name"] + "(" + op + ")",
                  "x".join([str(x) for x in out_shape]),
                  cur_param, first_connection]
        print_row(fields, positions)
        if len(pre_node) > 1:
            for i in range(1, len(pre_node)):
                fields = ["", "", "", pre_node[i]]
                print_row(fields, positions)
        total_params[0] += cur_param

    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        if op == "null" and i > 0:
            continue
        if op != "null" or i in heads:
            if show_shape:
                key = node["name"] + "_output" if op != "null" else node["name"]
                if key in shape_dict:
                    out_shape = shape_dict[key][1:]
        print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print("=" * line_length)
        else:
            print("_" * line_length)
    print("Total params: %s" % total_params[0])
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs={}, hide_weights=True):
    """Build a graphviz Digraph of the network (reference visualization.py
    plot_network).  Requires the ``graphviz`` package only at call time."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    draw_shape = False
    shape_dict = {}
    if shape is not None:
        draw_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    # color map mirroring the reference palette
    cm = ("#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3", "#fdb462",
          "#b3de69", "#fccde5")

    def looks_like_weight(name):
        if name.endswith("_weight") or name.endswith("_bias") or \
                name.endswith("_gamma") or name.endswith("_beta") or \
                name.endswith("_moving_var") or name.endswith("_moving_mean"):
            return True
        return False

    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        attrs = node.get("attr", {}) or {}
        label = name
        if op == "null":
            if looks_like_weight(name):
                if hide_weights:
                    hidden_nodes.add(name)
                continue
            attr = node_attr.copy()
            attr["shape"] = "oval"
            attr["fillcolor"] = cm[0]
        else:
            attr = node_attr.copy()
            if op == "Convolution":
                label = "Convolution\n%s/%s, %s" % (
                    attrs.get("kernel", "?"), attrs.get("stride", "(1, 1)"),
                    attrs.get("num_filter", "?"))
                attr["fillcolor"] = cm[1]
            elif op == "FullyConnected":
                label = "FullyConnected\n%s" % attrs.get("num_hidden", "?")
                attr["fillcolor"] = cm[1]
            elif op == "BatchNorm":
                attr["fillcolor"] = cm[3]
            elif op == "Activation" or op == "LeakyReLU":
                label = "%s\n%s" % (op, attrs.get("act_type", ""))
                attr["fillcolor"] = cm[2]
            elif op == "Pooling":
                label = "Pooling\n%s, %s/%s" % (
                    attrs.get("pool_type", "?"), attrs.get("kernel", "?"),
                    attrs.get("stride", "(1, 1)"))
                attr["fillcolor"] = cm[4]
            elif op in ("Concat", "Flatten", "Reshape"):
                attr["fillcolor"] = cm[5]
            elif op == "Softmax" or op == "SoftmaxOutput":
                attr["fillcolor"] = cm[6]
            else:
                attr["fillcolor"] = cm[7]
        dot.node(name=name, label=label, **attr)

    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        inputs = node["inputs"]
        for item in inputs:
            input_node = nodes[item[0]]
            input_name = input_node["name"]
            if input_name in hidden_nodes:
                continue
            attr = {"dir": "back", "arrowtail": "open"}
            if draw_shape:
                key = input_name + "_output" if input_node["op"] != "null" \
                    else input_name
                if key in shape_dict:
                    shape = shape_dict[key][1:]
                    label = "x".join([str(x) for x in shape])
                    attr["label"] = label
            dot.edge(tail_name=name, head_name=input_name, **attr)
    return dot

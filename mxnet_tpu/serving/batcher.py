"""Micro-batcher — coalesce in-flight requests into padded bucketed batches.

Why buckets: on XLA every novel input shape is a fresh compile, so a naive
batcher that flushes whatever happens to be queued (3 requests, then 7,
then 5...) compiles an executable per observed occupancy and spends its
life in the compiler.  Instead requests are padded up to a small fixed
set of power-of-two batch sizes — the same shape-quantization trick
``module/bucketing_module.py`` uses for variable-length training — and
:meth:`BucketedPredictor.warmup` pre-compiles every bucket once at
startup, so steady state never recompiles.  Batch size is the dominant
TPU-efficiency knob (PAPERS.md, "A Learned Performance Model for TPUs");
padding waste is bounded at <2x and observable via
``metrics.padded_items_total``.

Weights are shared across bucket executors through ``Predictor.reshape``
(live NDArrays pass through the rebind), so N buckets cost N compiled
programs but one copy of the parameters.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import profiler

__all__ = ["pow2_buckets", "BucketedPredictor", "MicroBatcher",
           "QueueFullError", "DeadlineExceededError", "ServerClosedError",
           "DrainTimeoutError"]


class QueueFullError(MXNetError):
    """Admission control rejected the request (queue at capacity)."""


class DeadlineExceededError(MXNetError):
    """The request's deadline passed before it reached an executor."""


class ServerClosedError(MXNetError):
    """The server is stopped (or stopping) and not accepting work."""


class DrainTimeoutError(MXNetError):
    """The drain deadline expired with work still outstanding: a wedged
    batcher worker must not hang retirement forever, so the remaining
    futures are force-cancelled with this typed error (callers retry on
    another replica)."""


def pow2_buckets(max_batch_size: int) -> tuple:
    """Power-of-two batch buckets up to and including ``max_batch_size``
    (which is appended as-is when it is not itself a power of two)."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return tuple(out)


class BucketedPredictor:
    """A family of shared-weight Predictors, one per batch bucket.

    Parameters
    ----------
    symbol, params, ctx, dtype
        As for :class:`mxnet_tpu.Predictor`.
    item_shapes : dict
        ``{input_name: per-item shape}`` — shapes WITHOUT the leading
        batch axis; every bucket ``b`` binds ``(b,) + item_shape``.
    buckets : sequence of int
        Allowed batch sizes, e.g. ``pow2_buckets(16)``.
    """

    def __init__(self, symbol, params, item_shapes: Dict[str, Sequence[int]],
                 buckets: Sequence[int], ctx=None, dtype=np.float32):
        from ..predictor import Predictor

        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets:
            raise ValueError("need at least one bucket")
        self.item_shapes = {k: tuple(v) for k, v in item_shapes.items()}
        self._dtype = np.dtype(dtype)
        base_b = self.buckets[-1]
        base = Predictor(symbol, params,
                         {k: (base_b,) + s
                          for k, s in self.item_shapes.items()},
                         ctx=ctx, dtype=dtype)
        self._preds = {base_b: base}
        for b in self.buckets[:-1]:
            self._preds[b] = base.reshape(
                {k: (b,) + s for k, s in self.item_shapes.items()})
        self.executor_calls = 0
        # compile-behaviour bookkeeping: buckets whose executable exists
        # because warmup() ran them, and how many post-warmup flushes hit
        # a bucket warmup never touched (the "steady state never
        # recompiles" contract is exactly cold_runs == 0)
        self.warmed_buckets = set()
        self.cold_runs = 0

    @property
    def max_batch_size(self):
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise MXNetError("batch of %d exceeds largest bucket %d"
                         % (n, self.buckets[-1]))

    def warmup(self):
        """Run one zero-filled forward per bucket so every compiled shape
        exists before traffic arrives — steady state never recompiles.

        With the persistent compile cache enabled
        (``MXNET_COMPILE_CACHE_DIR``) each bucket's forward primes
        through it: a warm cache (or an attached AOT bundle) makes this
        whole loop deserialize-only — zero XLA compiler invocations —
        which is what turns replica cold start and hot-swap shadow
        warming from minutes of compilation into milliseconds of I/O."""
        for b in self.buckets:
            pred = self._preds[b]
            for name, shape in self.item_shapes.items():
                pred.set_input(name, np.zeros((b,) + shape, self._dtype))
            pred._exec.forward(is_train=False)
            for out in pred.get_outputs():
                out.asnumpy()  # block until the compile+run finished
            self.warmed_buckets.add(b)

    def compiled_entries(self):
        """Every bucket's primed :class:`~mxnet_tpu.compile_cache.
        CachedFunction` wrapper (empty when the compile cache is off) —
        the input to ``checkpoint.save_aot_bundle``."""
        from ..compile_cache import CachedFunction

        out = []
        for b in self.buckets:
            for fn in self._preds[b]._exec._jit_cache.values():
                if isinstance(fn, CachedFunction):
                    out.append(fn)
        return out

    def forward_batch(self, items: List[Dict[str, np.ndarray]]):
        """Run one padded batch; returns per-item output lists (the batch
        axis is stripped from every output that carries one)."""
        n = len(items)
        b = self.bucket_for(n)
        if b not in self.warmed_buckets:
            self.cold_runs += 1
            self.warmed_buckets.add(b)
        pred = self._preds[b]
        for name, shape in self.item_shapes.items():
            buf = np.zeros((b,) + shape, self._dtype)
            for i, item in enumerate(items):
                buf[i] = item[name]
            pred.set_input(name, buf)
        pred._exec.forward(is_train=False)
        self.executor_calls += 1
        outs = [o.asnumpy() for o in pred.get_outputs()]
        per_item = []
        for i in range(n):
            per_item.append([o[i] if (o.ndim >= 1 and o.shape[0] == b) else o
                             for o in outs])
        return b, per_item


class _WorkItem:
    __slots__ = ("inputs", "future", "t_enqueue", "deadline")

    def __init__(self, inputs, future, deadline=None):
        self.inputs = inputs
        self.future = future
        self.t_enqueue = time.monotonic()
        self.deadline = deadline  # absolute monotonic seconds, or None


class MicroBatcher:
    """Bounded request queue + flush loop over one or more replicas.

    A flush happens when ``max_batch_size`` requests are queued or the
    oldest queued request has waited ``max_wait_us`` — whichever comes
    first.  Queued items stay in the queue until flush time, so
    ``len(queue)`` is the real backlog admission control sees.  Each
    replica (a :class:`BucketedPredictor`, typically one per device
    ``Context``) gets its own worker thread pulling from the shared
    queue, which is how multi-replica dispatch falls out for free.
    """

    def __init__(self, replicas: List[BucketedPredictor], metrics,
                 max_wait_us: int = 2000, max_queue: int = 256):
        if not replicas:
            raise ValueError("need at least one replica")
        self._replicas = replicas
        self._metrics = metrics
        self.max_batch_size = min(r.max_batch_size for r in replicas)
        self.max_wait_us = int(max_wait_us)
        self.max_queue = int(max_queue)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._inflight: set = set()  # _WorkItems dequeued but unfinished
        self._dead_workers: List[str] = []  # "name: exc" per crashed worker
        self._workers = [
            threading.Thread(target=self._run, args=(i,),
                             name="mxtpu-serving-%d" % i, daemon=True)
            for i in range(len(replicas))]
        self._started = False

    def start(self):
        if not self._started:
            self._started = True
            for w in self._workers:
                w.start()

    def swap_replicas(self, replicas: List[BucketedPredictor]):
        """Atomically replace the predictor families the worker threads
        execute on (the in-place checkpoint hot-swap).  Workers re-read
        their replica slot at the top of every flush, so the batch in
        flight finishes on the old weights and the very next flush runs
        on the new ones — no queue teardown, no dropped work."""
        if len(replicas) != len(self._replicas):
            raise ValueError("swap must keep the replica count (%d != %d)"
                             % (len(replicas), len(self._replicas)))
        with self._cv:
            self._replicas = list(replicas)
            self.max_batch_size = min(r.max_batch_size for r in replicas)
            self._cv.notify_all()

    def put(self, inputs, future, deadline=None):
        with self._cv:
            if self._closed:
                self._metrics.on_reject()
                raise ServerClosedError("server is stopped")
            if len(self._q) >= self.max_queue:
                self._metrics.on_reject()
                raise QueueFullError(
                    "queue full (%d pending); retry with backoff"
                    % len(self._q))
            item = _WorkItem(inputs, future, deadline)
            self._q.append(item)
            self._metrics.on_submit(len(self._q))
            self._cv.notify()
        return item

    def queue_depth(self):
        with self._cv:
            return len(self._q)

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty AND no dequeued batch is still
        executing — the drain barrier a graceful page-out waits on before
        releasing device memory.  Returns False on timeout (workers may
        re-check on a short poll: completions do not notify the CV)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            with self._cv:
                if not self._q and not self._inflight:
                    return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    def dead_workers(self):
        """``["thread-name: exception", ...]`` for worker threads that died
        on an unexpected error (health endpoints report these as degraded
        capacity — the server still works through its surviving replicas)."""
        with self._cv:
            return list(self._dead_workers)

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting work; with ``drain`` the workers flush whatever
        is queued before exiting, otherwise pending futures fail with
        :class:`ServerClosedError`.

        ``timeout`` (seconds) is a HARD drain deadline: if the workers
        have not flushed by then — a wedged executor, a worker stuck in a
        hung backend call — every still-pending future (queued or
        mid-batch) is force-cancelled with :class:`DrainTimeoutError`
        instead of hanging retirement forever.  ``None`` waits
        indefinitely (the legacy behaviour; :class:`InferenceServer`
        always passes its ``MXNET_SERVING_DRAIN_TIMEOUT_MS`` budget)."""
        with self._cv:
            self._closed = True
            if not drain:
                while self._q:
                    item = self._q.popleft()
                    item.future.set_exception(
                        ServerClosedError("server stopped before execution"))
                    self._metrics.on_fail()
            self._cv.notify_all()
        if self._started:
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            for w in self._workers:
                w.join(timeout if deadline is None
                       else max(0.0, deadline - time.monotonic()))
            if drain and any(w.is_alive() for w in self._workers):
                self._force_cancel()

    def release(self):
        """Drop the predictor references after :meth:`stop` so a paged-out
        server stops pinning device memory.  The worker threads have
        exited (or, post drain-timeout, can only be wedged inside a
        backend call that already holds its own transient reference), so
        nothing dereferences the replica list again; without this, a
        stopped in-process server keeps every bucket executable and the
        parameter arrays alive through this closure — the exact leak the
        platform's ``page_out`` must not have."""
        with self._cv:
            self._replicas = []

    def _force_cancel(self):
        """Drain deadline expired: fail every future still outstanding
        (queued or dequeued-but-unfinished) with the typed drain error.
        The wedged worker may eventually finish its batch — ``_execute``
        guards every ``set_result`` with ``done()`` so a late completion
        is dropped, never raised."""
        exc = DrainTimeoutError(
            "drain deadline exceeded with a worker still busy; "
            "outstanding requests force-cancelled")
        cancelled = 0
        with self._cv:
            while self._q:
                item = self._q.popleft()
                if not item.future.done():
                    item.future.set_exception(exc)
                    cancelled += 1
            for item in list(self._inflight):
                if not item.future.done():
                    item.future.set_exception(exc)
                    cancelled += 1
            self._inflight.clear()
            self._cv.notify_all()
        if cancelled:
            self._metrics.on_fail(cancelled)
            from .. import telemetry as _tm

            _tm.log_event("serving_drain_timeout", cancelled=cancelled,
                          dead_workers=self.dead_workers())
        return cancelled

    # -- worker side ------------------------------------------------------
    def _collect(self):
        """Return the next batch of work items, None when closed+empty."""
        with self._cv:
            while not self._q and not self._closed:
                self._cv.wait(0.05)
            if not self._q:
                return None  # closed and drained
            # wait for the batch to fill, bounded by the flush deadline of
            # the OLDEST queued item; closing flushes immediately
            flush_at = self._q[0].t_enqueue + self.max_wait_us / 1e6
            while (len(self._q) < self.max_batch_size
                   and not self._closed and self._q):
                now = time.monotonic()
                if now >= flush_at:
                    break
                self._cv.wait(min(flush_at - now, 0.05))
                if not self._q:
                    return []  # another replica stole the backlog
            batch = []
            while self._q and len(batch) < self.max_batch_size:
                batch.append(self._q.popleft())
            self._inflight.update(batch)
            self._metrics.on_dequeue(len(self._q))
            return batch

    def _run(self, slot):
        # _execute already confines per-batch executor failures to the
        # affected futures; anything escaping to here kills this replica's
        # thread, so record it — a fully-working-looking server with dead
        # workers is exactly the failure mode /healthz must surface.
        # The replica is re-read from its slot per flush so that
        # swap_replicas() takes effect between batches.
        try:
            while True:
                batch = self._collect()
                if batch is None:
                    return
                if not batch:
                    continue
                self._execute(self._replicas[slot], batch)
        except BaseException as exc:
            with self._cv:
                self._dead_workers.append(
                    "%s: %r" % (threading.current_thread().name, exc))
            self._metrics.on_worker_crash()
            raise

    def _execute(self, replica, batch):
        try:
            self._execute_inner(replica, batch)
        finally:
            with self._cv:
                self._inflight.difference_update(batch)

    def _execute_inner(self, replica, batch):
        now = time.monotonic()
        live = []
        for item in batch:
            if item.future.done():
                continue  # force-cancelled by a drain deadline
            if item.deadline is not None and now > item.deadline:
                item.future.set_exception(DeadlineExceededError(
                    "request waited past its deadline"))
                self._metrics.on_expire()
            else:
                live.append(item)
        if not live:
            return
        try:
            n = len(live)
            with profiler.Frame("serving/batch[n=%d]" % n,
                                category="serving"):
                bucket, results = replica.forward_batch(
                    [item.inputs for item in live])
            self._metrics.on_batch(bucket, n)
            done = time.monotonic()
            for item, res in zip(live, results):
                # a drain-deadline force-cancel may have failed this
                # future already; a late completion is dropped, not raised
                if not item.future.done():
                    item.future.set_result(res)
                    self._metrics.on_complete((done - item.t_enqueue) * 1e3)
        except Exception as exc:  # propagate to every waiting caller
            self._metrics.on_fail(len(live))
            for item in live:
                if not item.future.done():
                    item.future.set_exception(exc)

"""Router — the resilient serving front door over N InferenceServer replicas.

One :class:`InferenceServer` is a single point of failure: a replica
crash or a checkpoint reload drops requests.  The router makes the
serving tier survive any single failure with zero failed client
requests, with four cooperating mechanisms:

* **Health/load-aware dispatch** — every replica (in-process
  :class:`InferenceServer` or remote ``host:port`` backend) carries a
  liveness/readiness probe, an EWMA of observed latency, and an
  in-flight/queue-depth load estimate; dispatch picks the less-loaded of
  two random ready candidates (power-of-two-choices, which avoids the
  thundering-herd of strict least-loaded while staying O(1)).
* **Failure containment** — a per-replica circuit breaker
  (closed → open on consecutive failures → half-open probe → closed on
  success) keeps traffic off a sick replica while it recovers; a failed
  call is retried on another replica (bounded, carrying its original
  idempotent request id), so one replica's death is a latency blip, not
  an error.  Optional request hedging duplicates a slow call onto a
  second replica after a p99-based delay and takes the first answer —
  the classic tail-latency cure (requests are pure, so the duplicate is
  harmless by construction).
* **Per-SLO classes** — requests declare a class (``interactive`` /
  ``batch`` by default) mapping to a deadline budget and an admission
  priority; under queue pressure the sheddable classes are rejected
  first (HTTP 429 + ``Retry-After``), protecting interactive latency.
* **Zero-downtime hot-swap** — :meth:`Router.swap` rolls a new
  checkpoint through the fleet replica by replica: load params into a
  shadow replica, warm **every** batcher bucket on it (steady state
  never recompiles — the TVM compiled-artifact-reuse argument), atomically
  flip it into rotation, then drain and recycle the old one.  Capacity
  never drops below N-1 and no request ever sees a 5xx.

Every decision point is a ``mxnet_tpu.faults`` dotted op
(``serving.router.dispatch``, ``serving.replica.call``,
``serving.replica.<name>.call``, ``serving.router.hedge``,
``serving.router.swap``), so chaos scenarios drive the whole path
deterministically, and everything observable exports through
``mxnet_tpu.telemetry`` (RouterMetrics registry collector, breaker
transition counters, hedge wins, swap events, dispatch spans).
"""
from __future__ import annotations

import itertools
import json
import random
import threading
import time
from collections import deque
from concurrent.futures import (FIRST_COMPLETED, Future, ThreadPoolExecutor,
                                TimeoutError as FutureTimeoutError, wait)
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .. import faults
from .. import profiler
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from .batcher import (DeadlineExceededError, QueueFullError,
                      ServerClosedError)
from .metrics import _percentile
from .server import InferenceServer

__all__ = ["Router", "SLOClass", "RouterMetrics", "RouterError",
           "NoReplicaAvailableError", "RouterOverloadError",
           "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN"]

register_env("MXNET_SERVING_ROUTER_RETRIES", 2, int,
             "Max ADDITIONAL replicas a failed request is retried on "
             "before the router gives up.")
register_env("MXNET_SERVING_ROUTER_WORKERS", 16, int,
             "Router dispatcher thread-pool size (concurrent in-flight "
             "requests the router itself drives).")
register_env("MXNET_SERVING_BREAKER_THRESHOLD", 3, int,
             "Consecutive hard failures on one replica before its "
             "circuit breaker opens.")
register_env("MXNET_SERVING_BREAKER_COOLDOWN_MS", 1000.0, float,
             "How long an open breaker waits before letting one "
             "half-open probe request through.")
register_env("MXNET_SERVING_HEDGE_MS", 0.0, float,
             "Request hedging: 0 disables, >0 is a fixed delay in ms "
             "before duplicating a slow call onto a second replica, <0 "
             "derives the delay from the observed p99 latency.")
register_env("MXNET_SERVING_HEDGE_MIN_MS", 5.0, float,
             "Floor (and cold-start default) for the p99-derived hedge "
             "delay.")
register_env("MXNET_SERVING_SHED_PRESSURE", 0.75, float,
             "Queue-pressure fraction (aggregate backlog / aggregate "
             "queue capacity) beyond which sheddable SLO classes are "
             "rejected with 429 + Retry-After.")
register_env("MXNET_SERVING_PROBE_INTERVAL_MS", 200.0, float,
             "Background health-probe period for remote replicas.")
register_env("MXNET_SERVING_CALL_TIMEOUT_MS", 30000.0, float,
             "Per-replica call timeout when a request carries no "
             "deadline — a wedged replica becomes a breaker failure, "
             "not a hung client.")
register_env("MXNET_SERVING_REMOTE_CAPACITY", 256, int,
             "Assumed queue capacity of a remote replica for the "
             "pressure estimate (local replicas report their real "
             "max_queue).")
register_env("MXNET_SERVING_PROBE_FAILURES", 3, int,
             "Consecutive background-probe failures before a remote "
             "replica's cached health/readiness flips to down — one "
             "slow /healthz under load must not flap the breaker.")
register_env("MXNET_ROUTER_PROBE_FAILS", 0, int,
             "Consecutive health-probe failures before the router marks "
             "a backend dead (recovery still takes one success); 0 "
             "defers to MXNET_SERVING_PROBE_FAILURES (default 3).")
register_env("MXNET_SERVING_REGISTRY_SYNC_MS", 500.0, float,
             "Period at which a registry-attached router re-syncs its "
             "replica set against the shared live set.")
register_env("MXNET_GEN_TTFT_MS", 0.0, float,
             "Time-to-first-token budget (ms) of the default 'generate' "
             "SLO class; 0 means no budget.  Doubles as the admission "
             "deadline the router passes to the engine's pending queue.")
register_env("MXNET_GEN_ITL_MS", 0.0, float,
             "Inter-token-latency budget (ms) of the default 'generate' "
             "SLO class; 0 means no budget.  Gaps beyond it count in "
             "mxtpu_router_itl_violations_total.")

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_EWMA_ALPHA = 0.2


class RouterError(MXNetError):
    """Base class for router-level request failures."""


class NoReplicaAvailableError(RouterError):
    """Every routable replica was tried (or none was routable) and the
    request still failed — the HTTP 503 case."""


class RouterOverloadError(RouterError):
    """Admission control shed this request under queue pressure — the
    HTTP 429 + Retry-After case.  Sheddable classes go first."""

    def __init__(self, msg, retry_after=1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class SLOClass:
    """One service-level class: a default deadline budget plus an
    admission priority.  Higher ``priority`` numbers shed first;
    ``sheddable`` classes are rejected under queue pressure before any
    non-sheddable request is."""

    __slots__ = ("name", "deadline_ms", "priority", "sheddable",
                 "ttft_ms", "itl_ms")

    def __init__(self, name: str, deadline_ms: Optional[float] = None,
                 priority: int = 0, sheddable: bool = False,
                 ttft_ms: Optional[float] = None,
                 itl_ms: Optional[float] = None):
        self.name = name
        self.deadline_ms = deadline_ms
        self.priority = int(priority)
        self.sheddable = bool(sheddable)
        # streaming-generation budgets: a whole-request deadline is the
        # wrong unit for an open-ended token stream, so the generate
        # class budgets time-to-first-token and inter-token latency
        self.ttft_ms = ttft_ms
        self.itl_ms = itl_ms

    def __repr__(self):
        return ("SLOClass(%r, deadline_ms=%r, priority=%d, sheddable=%s, "
                "ttft_ms=%r, itl_ms=%r)"
                % (self.name, self.deadline_ms, self.priority,
                   self.sheddable, self.ttft_ms, self.itl_ms))


def default_slo_classes() -> Dict[str, SLOClass]:
    return {
        "interactive": SLOClass("interactive", priority=0, sheddable=False),
        "batch": SLOClass("batch", priority=1, sheddable=True),
        "generate": SLOClass(
            "generate", priority=0, sheddable=False,
            ttft_ms=env("MXNET_GEN_TTFT_MS", 0.0, float) or None,
            itl_ms=env("MXNET_GEN_ITL_MS", 0.0, float) or None),
    }


class _Request:
    __slots__ = ("rid", "slo", "inputs", "deadline", "t0")

    def __init__(self, rid, slo, inputs, deadline_ms):
        self.rid = rid
        self.slo = slo
        self.inputs = inputs
        self.t0 = time.monotonic()
        self.deadline = (self.t0 + deadline_ms / 1e3
                         if deadline_ms is not None else None)

    def remaining_ms(self) -> Optional[float]:
        """Deadline budget left, or raises when it is already spent —
        retries and hedges all charge against ONE budget."""
        if self.deadline is None:
            return None
        rem = (self.deadline - time.monotonic()) * 1e3
        if rem <= 0:
            raise DeadlineExceededError(
                "request %s exhausted its deadline budget" % self.rid)
        return rem


class RouterMetrics:
    """Registry-backed counters for one Router (a telemetry collector,
    like :class:`ServingMetrics`): per-SLO request/latency accounting,
    breaker transitions, failovers, hedges, sheds, swaps."""

    _LAT_SAMPLES = 4096

    def __init__(self):
        self._lock = threading.Lock()
        reg = self._registry = _telemetry.Registry()
        self._req = reg.labeled_counter("mxtpu_router_requests_total", "slo")
        self._done = reg.labeled_counter(
            "mxtpu_router_requests_completed", "slo")
        self._failed = reg.labeled_counter(
            "mxtpu_router_requests_failed", "slo")
        self._shed = reg.labeled_counter("mxtpu_router_requests_shed", "slo")
        self._expired = reg.labeled_counter(
            "mxtpu_router_requests_expired", "slo")
        self._retries = reg.counter("mxtpu_router_retries_total")
        self._streams = reg.labeled_counter(
            "mxtpu_router_streams_total", "slo")
        self._stream_resumes = reg.counter(
            "mxtpu_router_stream_resumes_total")
        self._itl_violations = reg.counter(
            "mxtpu_router_itl_violations_total")
        self._hedges = reg.counter("mxtpu_router_hedges_total")
        self._hedge_wins = reg.counter("mxtpu_router_hedge_wins_total")
        self._swaps = reg.counter("mxtpu_router_swaps_total")
        self._breaker = reg.labeled_counter(
            "mxtpu_router_breaker_transitions_total", "state")
        self._rep_failures = reg.labeled_counter(
            "mxtpu_router_replica_failures_total", "replica")
        self._g_replicas = reg.gauge("mxtpu_router_replicas")
        self._g_ready = reg.gauge("mxtpu_router_replicas_ready")
        self._g_pressure = reg.gauge("mxtpu_router_pressure_pct")
        self._lat = {}  # slo -> deque of latency ms
        _telemetry.register_collector(self)

    # -- update hooks ------------------------------------------------------
    def on_submit(self, slo):
        self._req.inc(slo)

    def on_complete(self, slo, latency_ms):
        self._done.inc(slo)
        with self._lock:
            self._lat.setdefault(
                slo, deque(maxlen=self._LAT_SAMPLES)).append(latency_ms)

    def on_fail(self, slo):
        self._failed.inc(slo)

    def on_shed(self, slo):
        self._shed.inc(slo)

    def on_expire(self, slo):
        self._expired.inc(slo)

    def on_retry(self):
        self._retries.inc()

    def on_stream(self, slo):
        self._streams.inc(slo)

    def on_stream_resume(self):
        self._stream_resumes.inc()

    def on_itl_violation(self):
        self._itl_violations.inc()

    def on_hedge(self):
        self._hedges.inc()

    def on_hedge_win(self):
        self._hedge_wins.inc()

    def on_swap(self):
        self._swaps.inc()

    def on_breaker(self, state):
        self._breaker.inc(state)

    def on_replica_failure(self, name):
        self._rep_failures.inc(name)

    def set_topology(self, total, ready, pressure):
        self._g_replicas.set(total)
        self._g_ready.set(ready)
        self._g_pressure.set(int(pressure * 100))

    # -- export ------------------------------------------------------------
    def latency_quantile(self, q, slo=None):
        """Latency quantile in ms over completed requests (one class, or
        pooled); None until any request completed."""
        with self._lock:
            if slo is None:
                vals = [v for d in self._lat.values() for v in d]
            else:
                vals = list(self._lat.get(slo, ()))
        if not vals:
            return None
        return _percentile(sorted(vals), q)

    def snapshot(self):
        out = {
            "requests": self._req.snapshot(),
            "completed": self._done.snapshot(),
            "failed": self._failed.snapshot(),
            "shed": self._shed.snapshot(),
            "expired": self._expired.snapshot(),
            "retries": self._retries.value,
            "streams": self._streams.snapshot(),
            "stream_resumes": self._stream_resumes.value,
            "itl_violations": self._itl_violations.value,
            "hedges": self._hedges.value,
            "hedge_wins": self._hedge_wins.value,
            "swaps": self._swaps.value,
            "breaker_transitions": self._breaker.snapshot(),
            "replica_failures": self._rep_failures.snapshot(),
            "replicas": self._g_replicas.value,
            "replicas_ready": self._g_ready.value,
        }
        with self._lock:
            slos = list(self._lat)
        for slo in slos:
            out["latency_ms_p50_%s" % slo] = self.latency_quantile(.50, slo)
            out["latency_ms_p99_%s" % slo] = self.latency_quantile(.99, slo)
        return out

    def render_text(self):
        text = self._registry.render_prometheus()
        lines = [text] if text else []
        with self._lock:
            slos = list(self._lat)
        for slo in sorted(slos):
            for q, v in (("0.5", self.latency_quantile(.50, slo)),
                         ("0.99", self.latency_quantile(.99, slo))):
                if v is not None:
                    lines.append(
                        'mxtpu_router_latency_ms{slo="%s",quantile="%s"} '
                        '%.3f\n' % (slo, q, v))
        return "".join(lines)

    def render_prometheus(self):
        """Collector hook for ``telemetry.render_prometheus()``."""
        return self.render_text()


class _Replica:
    """Shared replica state machine: circuit breaker + load estimate.

    Breaker contract: CLOSED admits everything; ``threshold`` consecutive
    hard failures OPEN it; after ``cooldown`` the next pick transitions to
    HALF_OPEN and admits exactly one probe request — success re-CLOSEs,
    failure re-OPENs with a fresh cooldown.  Deadline expiries and
    queue-full rejections are *load* signals, not faults: they never
    advance the failure count.
    """

    kind = "base"

    def __init__(self, name, router):
        self.name = name
        self._router = router
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.inflight = 0
        self.ewma_ms = 0.0
        self.calls = 0
        # scale-in / hot-removal: a draining replica finishes its
        # in-flight work but never receives a new dispatch
        self.draining = False

    # -- breaker -----------------------------------------------------------
    def _transition(self, state):
        self.state = state
        self._router.metrics.on_breaker(state)
        _telemetry.log_event("router_breaker", replica=self.name,
                             state=state)

    def routable(self, now) -> bool:
        if self.draining:
            return False
        with self._lock:
            if self.state == BREAKER_OPEN and \
                    now - self._opened_at >= self._router.breaker_cooldown_s:
                self._transition(BREAKER_HALF_OPEN)
                self._probe_inflight = False
            if self.state == BREAKER_OPEN:
                return False
            if self.state == BREAKER_HALF_OPEN and self._probe_inflight:
                return False  # one probe at a time
        return self.ready()

    def try_reserve(self) -> bool:
        """Claim the right to dispatch one request here.  CLOSED admits
        everything; HALF_OPEN atomically admits exactly ONE probe —
        ``routable`` alone cannot enforce that, because two dispatcher
        threads may both read half-open+idle before either begins its
        call (the classic check-then-act race).  The reservation is
        released by ``end_call`` (any outcome) or ``release``."""
        with self._lock:
            if self.state == BREAKER_OPEN:
                return False
            if self.state == BREAKER_HALF_OPEN:
                if self._probe_inflight:
                    return False
                self._probe_inflight = True
            return True

    def release(self):
        """Undo a ``try_reserve`` that never became a call."""
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                self._probe_inflight = False

    def begin_call(self):
        with self._lock:
            self.inflight += 1
            self.calls += 1
            if self.state == BREAKER_HALF_OPEN:
                self._probe_inflight = True

    def end_call(self, ok: Optional[bool], latency_ms: float):
        """``ok=None`` is the neutral outcome (deadline/queue-full):
        load bookkeeping only, breaker untouched."""
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            if ok is None:
                if self.state == BREAKER_HALF_OPEN:
                    self._probe_inflight = False
                return
            if ok:
                self._failures = 0
                self.ewma_ms = (latency_ms if self.ewma_ms == 0.0 else
                                _EWMA_ALPHA * latency_ms +
                                (1 - _EWMA_ALPHA) * self.ewma_ms)
                if self.state != BREAKER_CLOSED:
                    self._probe_inflight = False
                    self._transition(BREAKER_CLOSED)
            else:
                self._failures += 1
                if self.state == BREAKER_HALF_OPEN or \
                        self._failures >= self._router.breaker_threshold:
                    if self.state != BREAKER_OPEN:
                        self._transition(BREAKER_OPEN)
                    self._opened_at = time.monotonic()
                    self._probe_inflight = False
        if ok is False:
            self._router.metrics.on_replica_failure(self.name)

    # -- load --------------------------------------------------------------
    def score(self) -> float:
        """Lower routes first: EWMA latency scaled by outstanding work."""
        return (self.ewma_ms or 1.0) * (1.0 + self.inflight
                                        + self.queue_depth())

    def describe(self) -> dict:
        return {"name": self.name, "kind": self.kind, "state": self.state,
                "ready": self.ready(), "inflight": self.inflight,
                "ewma_ms": round(self.ewma_ms, 3), "calls": self.calls,
                "queue_depth": self.queue_depth(),
                "draining": self.draining}

    # -- backend interface -------------------------------------------------
    def ready(self) -> bool:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def queue_depth(self) -> int:
        return 0

    def capacity(self) -> int:
        return env("MXNET_SERVING_REMOTE_CAPACITY", 256, int)

    def call(self, inputs, deadline_ms, request_id, slo):
        raise NotImplementedError

    def supports_generate(self) -> bool:
        return False

    def generate_stream(self, prompt, max_new_tokens, deadline_ms,
                        request_id, slo):
        """Iterator of generated token ids; raising mid-iteration is the
        resume-on-another-replica signal."""
        raise NotImplementedError


class _LocalReplica(_Replica):
    """An in-process :class:`InferenceServer` behind the router."""

    kind = "local"

    def __init__(self, name, server: InferenceServer, router):
        super().__init__(name, router)
        self.server = server

    def ready(self):
        return self.server.ready()

    def alive(self):
        return not self.server._stopped

    def queue_depth(self):
        try:
            return self.server.queue_depth()
        except Exception:
            return 0

    def capacity(self):
        return self.server._batcher.max_queue

    def call(self, inputs, deadline_ms, request_id, slo):
        fut = self.server.submit(deadline_ms=deadline_ms, **inputs)
        timeout_ms = deadline_ms if deadline_ms is not None else \
            env("MXNET_SERVING_CALL_TIMEOUT_MS", 30000.0, float)
        try:
            # slack past the deadline: the server's own expiry wins the
            # race and surfaces as DeadlineExceededError, not a timeout
            return fut.result(timeout=timeout_ms / 1e3 + 5.0)
        except FutureTimeoutError:
            raise RouterError(
                "replica %s timed out after %.0fms (request %s)"
                % (self.name, timeout_ms, request_id))

    def supports_generate(self):
        return self.server._generator is not None

    def generate_stream(self, prompt, max_new_tokens, deadline_ms,
                        request_id, slo):
        stream = self.server.submit_generate(
            prompt, max_new_tokens, deadline_ms=deadline_ms)
        return iter(stream)


class _RemoteReplica(_Replica):
    """A remote ``host:port`` InferenceServer HTTP backend."""

    kind = "remote"

    def __init__(self, name, addr: str, router):
        super().__init__(name, router)
        self.addr = addr
        self._base = "http://%s" % addr
        self._probe_ready = None  # cached by the background probe thread
        self._probe_alive = None
        # debounce: one slow /healthz under load must not flap the
        # replica out of rotation — K consecutive failures flip it down,
        # one success flips it straight back up
        self._probe_k = max(1, env("MXNET_ROUTER_PROBE_FAILS", 0, int)
                            or env("MXNET_SERVING_PROBE_FAILURES", 3, int))
        self._alive_misses = 0
        self._ready_misses = 0

    def _get(self, path, timeout=2.0):
        import urllib.request

        with urllib.request.urlopen(self._base + path,
                                    timeout=timeout) as resp:
            return resp.status

    def _probe(self):
        """Refresh the cached liveness/readiness (background thread).
        Success is believed immediately; failure only after
        ``MXNET_SERVING_PROBE_FAILURES`` consecutive misses — except
        while the cache is still unset (first contact), where a miss
        counts at once so a never-up backend is not routed to."""
        faults.fire("serving.replica.probe")
        try:
            ok = self._get("/healthz") == 200
        except Exception:
            ok = False
        if ok:
            self._alive_misses = 0
            self._probe_alive = True
        else:
            self._alive_misses += 1
            if self._probe_alive is None or \
                    self._alive_misses >= self._probe_k:
                self._probe_alive = False
        try:
            ok = self._get("/readyz") == 200
        except Exception:
            ok = False
        if ok:
            self._ready_misses = 0
            self._probe_ready = True
        else:
            self._ready_misses += 1
            if self._probe_ready is None or \
                    self._ready_misses >= self._probe_k:
                self._probe_ready = False

    def ready(self):
        if self._probe_ready is None:
            try:
                self._probe()
            except Exception:
                return False
        return bool(self._probe_ready)

    def alive(self):
        if self._probe_alive is None:
            self.ready()
        return bool(self._probe_alive)

    def queue_depth(self):
        return 0  # remote backlog is not visible; inflight covers it

    def call(self, inputs, deadline_ms, request_id, slo):
        import urllib.error
        import urllib.request

        body = json.dumps({"inputs": {
            k: np.asarray(v).tolist() for k, v in inputs.items()}}).encode()
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": request_id, "X-SLO-Class": slo}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = "%.3f" % deadline_ms
        timeout_ms = deadline_ms if deadline_ms is not None else \
            env("MXNET_SERVING_CALL_TIMEOUT_MS", 30000.0, float)
        req = urllib.request.Request(self._base + "/predict", data=body,
                                     headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_ms / 1e3 + 5.0) as resp:
                outs = json.loads(resp.read())["outputs"]
                return [np.asarray(o, np.float32) for o in outs]
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")[:200]
            exc.close()
            if exc.code == 504:
                raise DeadlineExceededError(detail)
            if exc.code in (429, 503):
                raise QueueFullError("replica %s rejected: %s"
                                     % (self.name, detail))
            raise RouterError("replica %s HTTP %d: %s"
                              % (self.name, exc.code, detail))

    def supports_generate(self):
        # not probeable cheaply: assume yes; a generator-less backend
        # answers 404 which surfaces as RouterError -> failover
        return True

    def generate_stream(self, prompt, max_new_tokens, deadline_ms,
                        request_id, slo):
        import urllib.error
        import urllib.request

        payload = {"prompt": [int(t) for t in prompt]}
        if max_new_tokens is not None:
            payload["max_new_tokens"] = int(max_new_tokens)
        if deadline_ms is not None:
            payload["deadline_ms"] = float(deadline_ms)
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": request_id, "X-SLO-Class": slo}
        timeout_ms = env("MXNET_SERVING_CALL_TIMEOUT_MS", 30000.0, float)
        req = urllib.request.Request(
            self._base + "/generate", data=json.dumps(payload).encode(),
            headers=headers)
        try:
            resp = urllib.request.urlopen(req, timeout=timeout_ms / 1e3)
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")[:200]
            exc.close()
            if exc.code == 429 or exc.code == 503:
                raise QueueFullError("replica %s rejected generate: %s"
                                     % (self.name, detail))
            if exc.code == 504:
                raise DeadlineExceededError(detail)
            raise RouterError("replica %s HTTP %d: %s"
                              % (self.name, exc.code, detail))

        def _iter():
            # NDJSON lines, one token each, until the done/error line;
            # connection close without one means the replica died
            with resp:
                done = False
                for line in resp:
                    obj = json.loads(line)
                    if "error" in obj:
                        raise RouterError("replica %s stream failed: %s"
                                          % (self.name, obj["error"]))
                    if obj.get("done"):
                        done = True
                        break
                    yield int(obj["token"])
                if not done:
                    raise RouterError(
                        "replica %s stream closed without done marker"
                        % self.name)
        return _iter()

    def swap(self, prefix, epoch, timeout=600.0):
        """Remote in-place hot-swap via ``POST /swap`` (the server warms
        every bucket on the new params before its atomic flip)."""
        import urllib.request

        body = json.dumps({"prefix": prefix, "epoch": int(epoch)}).encode()
        req = urllib.request.Request(
            self._base + "/swap", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())


class Router:
    """Health-aware front door over N serving replicas.

    Parameters
    ----------
    backends : sequence of InferenceServer | "host:port" str
        The replica set: in-process servers and/or remote HTTP backends
        (an :class:`InferenceServer` exposed via ``serve_http``).  Mixed
        sets are fine.
    slo_classes : dict name -> SLOClass, optional
        Defaults to ``interactive`` (never shed) + ``batch`` (sheddable).
    retries, breaker_threshold, breaker_cooldown_ms, hedge_ms,
    shed_pressure, workers
        Override the corresponding ``MXNET_SERVING_*`` env defaults.
    seed : int
        Seeds the power-of-two-choices RNG, so a chaos run's dispatch
        sequence is reproducible.
    registry : ReplicaRegistry | RegistryClient, optional
        A shared replica live-set (``serving.registry``).  The router
        syncs its replica set against it in the background
        (``MXNET_SERVING_REGISTRY_SYNC_MS``): members it has never seen
        are added, members that left or were evicted are drained and
        removed.  N routers attached to one registry converge on the
        same fleet — the front door stops being a single point of
        failure.  With a registry, ``backends`` may be empty.
    model : str, optional
        Restrict registry discovery to members whose registration meta
        carries this ``model`` label (members without one count as
        ``"default"``).  N model-scoped routers can then share one
        registry — the multi-model platform's per-model live view.
    """

    def __init__(self, backends: Sequence[Union[InferenceServer, str]] = (),
                 slo_classes: Optional[Dict[str, SLOClass]] = None,
                 retries: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 shed_pressure: Optional[float] = None,
                 workers: Optional[int] = None, seed: int = 0,
                 registry=None, registry_sync_ms: Optional[float] = None,
                 model: Optional[str] = None):
        if not backends and registry is None:
            raise ValueError("need at least one backend replica "
                             "(or a registry to discover them from)")
        self.metrics = RouterMetrics()
        self.retries = env("MXNET_SERVING_ROUTER_RETRIES", 2, int) \
            if retries is None else int(retries)
        self.breaker_threshold = \
            env("MXNET_SERVING_BREAKER_THRESHOLD", 3, int) \
            if breaker_threshold is None else int(breaker_threshold)
        self.breaker_cooldown_s = (
            env("MXNET_SERVING_BREAKER_COOLDOWN_MS", 1000.0, float)
            if breaker_cooldown_ms is None else float(breaker_cooldown_ms)
        ) / 1e3
        self.hedge_ms = env("MXNET_SERVING_HEDGE_MS", 0.0, float) \
            if hedge_ms is None else float(hedge_ms)
        self.shed_pressure = env("MXNET_SERVING_SHED_PRESSURE", 0.75, float) \
            if shed_pressure is None else float(shed_pressure)
        n_workers = env("MXNET_SERVING_ROUTER_WORKERS", 16, int) \
            if workers is None else int(workers)
        self.slo_classes = dict(slo_classes) if slo_classes is not None \
            else default_slo_classes()

        self._lock = threading.Lock()
        self._swap_lock = threading.Lock()  # one rolling swap at a time
        self._replicas: List[_Replica] = []
        for i, b in enumerate(backends):
            name = "r%d" % i
            if isinstance(b, str):
                self._replicas.append(_RemoteReplica(name, b, self))
            else:
                self._replicas.append(_LocalReplica(name, b, self))
        self._name_seq = itertools.count(len(self._replicas))
        # servers the router itself created (swap shadows): it owns their
        # lifecycle; caller-provided backends stay the caller's
        self._owned: List[InferenceServer] = []
        self._closed = False
        self._rng = random.Random(seed)
        self._rid = itertools.count()
        self._pool = ThreadPoolExecutor(
            max_workers=n_workers, thread_name_prefix="mxtpu-router")
        self._call_pool = ThreadPoolExecutor(
            max_workers=2 * n_workers + 2,
            thread_name_prefix="mxtpu-router-call")
        self._httpd = None
        self._http_thread = None
        self._probe_stop = threading.Event()
        self._probe_thread = None
        if any(isinstance(r, _RemoteReplica) for r in self._replicas):
            self._ensure_probe_thread()
        # registry-driven replica discovery (router replication): names
        # under registry management are synced against the shared live
        # set; constructor-passed backends stay the caller's.
        self._registry = registry
        # per-model registry view: with model=<name> only registry
        # members whose meta carries that model label are adopted
        # (absent label == "default"), so N model-scoped routers share
        # ONE registry instead of one registry per model.
        self._model = model
        self._registry_names: set = set()
        self._registry_gen = -1
        self._registry_stop = threading.Event()
        self._registry_thread = None
        if registry is not None:
            self._registry_sync_s = (
                env("MXNET_SERVING_REGISTRY_SYNC_MS", 500.0, float)
                if registry_sync_ms is None else float(registry_sync_ms)
            ) / 1e3
            self._sync_registry()  # first sync before taking traffic
            self._registry_thread = threading.Thread(
                target=self._registry_loop, name="mxtpu-router-regsync",
                daemon=True)
            self._registry_thread.start()

    def _ensure_probe_thread(self):
        if self._probe_thread is None:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="mxtpu-router-probe",
                daemon=True)
            self._probe_thread.start()

    # -- topology ----------------------------------------------------------
    def replicas(self) -> List[_Replica]:
        with self._lock:
            return list(self._replicas)

    def describe(self) -> List[dict]:
        return [r.describe() for r in self.replicas()]

    def pressure(self) -> float:
        """Aggregate backlog / aggregate queue capacity across replicas —
        the admission-control load signal sheddable classes are gated on
        (and the autoscaler's primary scale signal).  Draining replicas
        contribute their backlog but no capacity: retiring a replica
        must RAISE measured pressure, not mask it."""
        cap = 0
        load = 0
        for r in self.replicas():
            if not r.draining:
                cap += r.capacity()
            load += (r.queue_depth() if isinstance(r, _LocalReplica)
                     else r.inflight)
        return (load / cap) if cap else 1.0

    # -- dynamic topology (autoscaler + registry sync) ---------------------
    def add_replica(self, backend, name: Optional[str] = None) -> str:
        """Put a new backend into rotation; returns its replica name.
        The autoscaler's scale-out actuation and the registry sync both
        land here."""
        if self._closed:
            raise ServerClosedError("router is closed")
        with self._lock:
            if name is None:
                name = "r%d" % next(self._name_seq)
            if any(r.name == name for r in self._replicas):
                raise MXNetError("replica name %r already in rotation"
                                 % name)
            if isinstance(backend, str):
                rep = _RemoteReplica(name, backend, self)
            else:
                rep = _LocalReplica(name, backend, self)
            self._replicas.append(rep)
        if isinstance(rep, _RemoteReplica):
            self._ensure_probe_thread()
        _telemetry.log_event("router_topology", op="add", replica=name,
                             replica_kind=rep.kind)
        self._update_topology_metrics()
        return name

    def remove_replica(self, name: str, drain: bool = True,
                       drain_timeout_ms: Optional[float] = None,
                       wait: bool = True):
        """Take one replica out of rotation.  It is flipped to draining
        first (no new dispatch; requests in flight finish), then dropped
        from the set once idle or when the drain deadline
        (``MXNET_SERVING_DRAIN_TIMEOUT_MS``) expires — a wedged replica
        must not hang retirement forever.  With ``wait=False`` the
        drain-then-drop runs in a background thread (the registry sync
        path, which must stay responsive).  Returns the removed
        replica's backend (or None for ``wait=False`` / unknown
        names)."""
        with self._lock:
            rep = next((r for r in self._replicas if r.name == name), None)
            if rep is None:
                return None
            rep.draining = True
        _telemetry.log_event("router_topology", op="drain", replica=name)

        def _finish():
            if drain:
                deadline = time.monotonic() + (
                    env("MXNET_SERVING_DRAIN_TIMEOUT_MS", 30000.0, float)
                    if drain_timeout_ms is None else float(drain_timeout_ms)
                ) / 1e3
                while time.monotonic() < deadline:
                    if rep.inflight == 0 and rep.queue_depth() == 0:
                        break
                    time.sleep(0.01)
            with self._lock:
                if rep in self._replicas:
                    self._replicas.remove(rep)
            _telemetry.log_event("router_topology", op="remove",
                                 replica=name, replica_kind=rep.kind)
            self._update_topology_metrics()
            return (rep.server if isinstance(rep, _LocalReplica)
                    else rep.addr)

        if wait:
            return _finish()
        threading.Thread(target=_finish, name="mxtpu-router-drain-%s" % name,
                         daemon=True).start()
        return None

    def _sync_registry(self):
        """One reconciliation pass against the shared registry: add
        members this router has never seen, drain-and-remove the ones
        that deregistered or were evicted.  Gen-gated, so the steady
        state costs one integer fetch."""
        try:
            live = self._registry.live()
        except Exception:
            return  # registry blip: keep serving the last-known fleet
        if live["gen"] == self._registry_gen:
            return
        self._registry_gen = live["gen"]
        metas = live.get("meta") or {}
        members = live["replicas"]
        if self._model is not None:
            members = {
                name: backend for name, backend in members.items()
                if ((metas.get(name) or {}).get("model") or "default")
                == self._model}
        current = {r.name for r in self.replicas()}
        for name, backend in members.items():
            if name not in current:
                try:
                    self.add_replica(backend, name=name)
                except MXNetError:
                    pass  # raced another sync pass
                self._registry_names.add(name)
        for name in sorted(self._registry_names - set(members)):
            self._registry_names.discard(name)
            self.remove_replica(name, wait=False)

    def sync_registry(self):
        """Force one registry reconciliation pass right now (the
        background loop runs every MXNET_SERVING_REGISTRY_SYNC_MS).  The
        platform front door calls this after a fault-in so the first
        request sees the fresh replica instead of a 500ms-stale view."""
        self._sync_registry()

    def _registry_loop(self):
        while not self._registry_stop.wait(self._registry_sync_s):
            self._sync_registry()

    def signals(self) -> dict:
        """The autoscaler's input: one consistent snapshot of the
        pressure/SLO/breaker/shed signals this router already exports as
        telemetry."""
        reps = self.replicas()
        now = time.monotonic()
        snap = self.metrics.snapshot()
        p99 = {}
        budget = {}
        for slo, cls in self.slo_classes.items():
            # streaming classes budget TTFT instead of a whole-request
            # deadline; their latency samples ARE TTFT observations
            bud = cls.deadline_ms if cls.deadline_ms is not None \
                else cls.ttft_ms
            if bud is not None:
                v = self.metrics.latency_quantile(0.99, slo)
                if v is not None:
                    p99[slo] = v
                    budget[slo] = bud
        return {
            "pressure": self.pressure(),
            "replicas": len(reps),
            "ready": sum(1 for r in reps if r.routable(now)),
            "draining": sum(1 for r in reps if r.draining),
            "breakers_open": sum(1 for r in reps
                                 if r.state != BREAKER_CLOSED),
            "shed_total": sum(snap["shed"].values()),
            "expired_total": sum(snap["expired"].values()),
            "stream_resumes": snap["stream_resumes"],
            "p99_ms": p99,
            "deadline_ms": budget,
        }

    def _update_topology_metrics(self, pressure=None):
        reps = self.replicas()
        now = time.monotonic()
        self.metrics.set_topology(
            len(reps), sum(1 for r in reps if r.routable(now)),
            self.pressure() if pressure is None else pressure)

    def _probe_loop(self):
        interval = env("MXNET_SERVING_PROBE_INTERVAL_MS", 200.0, float) / 1e3
        while not self._probe_stop.wait(interval):
            for r in self.replicas():
                if isinstance(r, _RemoteReplica):
                    try:
                        r._probe()
                    except Exception:
                        pass
            self._update_topology_metrics()

    # -- request path ------------------------------------------------------
    def submit(self, slo: str = "interactive",
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None, **inputs) -> Future:
        """Admit one request and return a Future for its per-item output
        list.  Raises :class:`RouterOverloadError` synchronously when
        admission control sheds this SLO class, ``ServerClosedError``
        after :meth:`close`; the future raises
        :class:`NoReplicaAvailableError` when every routable replica was
        exhausted or ``DeadlineExceededError`` past the budget."""
        if self._closed:
            raise ServerClosedError("router is closed")
        cls = self.slo_classes.get(slo)
        if cls is None:
            raise MXNetError("unknown SLO class %r (one of %s)"
                             % (slo, sorted(self.slo_classes)))
        pressure = self.pressure()
        if cls.sheddable and pressure >= self.shed_pressure:
            self.metrics.on_shed(slo)
            _telemetry.log_event("router_shed", slo=slo,
                                 pressure=round(pressure, 3))
            raise RouterOverloadError(
                "shedding %r traffic at %.0f%% queue pressure"
                % (slo, pressure * 100))
        if deadline_ms is None:
            deadline_ms = cls.deadline_ms
        rid = request_id if request_id is not None \
            else "req-%d" % next(self._rid)
        self.metrics.on_submit(slo)
        req = _Request(rid, slo, inputs, deadline_ms)
        return self._pool.submit(self._dispatch, req)

    def predict(self, slo: str = "interactive",
                deadline_ms: Optional[float] = None,
                **inputs) -> List[np.ndarray]:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(slo=slo, deadline_ms=deadline_ms,
                           **inputs).result()

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 slo: str = "generate",
                 deadline_ms: Optional[float] = None,
                 request_id: Optional[str] = None):
        """Stream generated tokens through the fleet: returns an
        iterator of token ids, resumable across replica failures.

        The stream dispatches to a generate-capable replica
        (power-of-two-choices, breakers respected); if the replica dies
        MID-STREAM the router resumes on another one by re-submitting
        ``prompt + tokens emitted so far`` (greedy decode is
        deterministic, so the client-visible stream continues seamlessly
        with zero duplicated or dropped tokens —
        ``mxtpu_router_stream_resumes_total`` counts the seams).  Hedging
        is not applied to streams: a duplicated stream would decode the
        same tokens twice for no tail-latency win on an open-ended
        response; failover covers the slow-replica case instead.

        ``deadline_ms`` (default: the class ``ttft_ms``) bounds
        ADMISSION — time queued before the first token — not the whole
        stream; inter-token gaps beyond the class ``itl_ms`` budget
        count in ``mxtpu_router_itl_violations_total``.  Raises
        :class:`RouterOverloadError` synchronously when the class is
        shed; the iterator raises :class:`NoReplicaAvailableError` when
        every capable replica failed."""
        if self._closed:
            raise ServerClosedError("router is closed")
        cls = self.slo_classes.get(slo)
        if cls is None:
            raise MXNetError("unknown SLO class %r (one of %s)"
                             % (slo, sorted(self.slo_classes)))
        pressure = self.pressure()
        if cls.sheddable and pressure >= self.shed_pressure:
            self.metrics.on_shed(slo)
            _telemetry.log_event("router_shed", slo=slo,
                                 pressure=round(pressure, 3))
            raise RouterOverloadError(
                "shedding %r traffic at %.0f%% queue pressure"
                % (slo, pressure * 100))
        if max_new_tokens is None:
            max_new_tokens = env("MXNET_GEN_MAX_NEW_TOKENS", 64, int)
        rid = request_id if request_id is not None \
            else "gen-%d" % next(self._rid)
        self.metrics.on_submit(slo)
        self.metrics.on_stream(slo)
        return self._generate_iter(cls, rid, prompt, int(max_new_tokens),
                                   deadline_ms)

    def _generate_iter(self, cls, rid, prompt, max_new, deadline_ms):
        t0 = time.monotonic()
        cur = [int(t) for t in prompt]
        remaining = max_new
        emitted = 0
        failures = 0
        last_exc = None
        ttft_budget = deadline_ms if deadline_ms is not None \
            else cls.ttft_ms
        itl_budget = cls.itl_ms
        while remaining > 0:
            faults.fire("serving.router.dispatch")
            tried = set()
            rep = None
            while True:
                cand = self._pick(tried)
                if cand is None:
                    break
                tried.add(cand.name)
                if cand.supports_generate():
                    rep = cand
                    break
                cand.release()
            if rep is None:
                self.metrics.on_fail(cls.name)
                raise NoReplicaAvailableError(
                    "generate %s: no generate-capable replica (tried %s):"
                    " %r" % (rid, sorted(tried) or "none", last_exc)) \
                    from last_exc
            rep.begin_call()
            ok = None
            t_call = time.monotonic()
            made_progress = False
            try:
                faults.fire("serving.replica.call")
                faults.fire("serving.replica.%s.call" % rep.name)
                stream = rep.generate_stream(
                    cur, remaining,
                    ttft_budget if emitted == 0 else None, rid, cls.name)
                t_prev = time.monotonic()
                for tok in stream:
                    now = time.monotonic()
                    if emitted == 0:
                        # TTFT is the stream's per-SLO latency sample
                        self.metrics.on_complete(cls.name,
                                                 (now - t0) * 1e3)
                    elif itl_budget and (now - t_prev) * 1e3 > itl_budget:
                        self.metrics.on_itl_violation()
                    t_prev = now
                    tok = int(tok)
                    cur.append(tok)
                    emitted += 1
                    remaining -= 1
                    made_progress = True
                    failures = 0
                    yield tok
                    if remaining <= 0:
                        break
                if remaining > 0 and not made_progress:
                    raise RouterError(
                        "replica %s returned an empty stream" % rep.name)
                ok = True
            except DeadlineExceededError:
                ok = None  # admission budget died, not the replica
                self.metrics.on_expire(cls.name)
                raise
            except QueueFullError as exc:
                ok = None  # load signal, breaker-neutral
                last_exc = exc
                failures += 1
            except GeneratorExit:
                ok = None  # consumer abandoned the stream
                raise
            except BaseException as exc:
                ok = False
                last_exc = exc
                failures += 1
            finally:
                rep.end_call(ok, (time.monotonic() - t_call) * 1e3)
            if ok:
                return  # budget reached or EOS: clean end of stream
            if failures > self.retries:
                self.metrics.on_fail(cls.name)
                raise NoReplicaAvailableError(
                    "generate %s failed after %d attempts: %r"
                    % (rid, failures, last_exc)) from last_exc
            # resume on another replica: re-submit prompt + emitted
            # tokens (deterministic greedy decode -> seamless stream)
            if made_progress or emitted:
                self.metrics.on_stream_resume()
            else:
                self.metrics.on_retry()
            _telemetry.log_event("router_stream_resume", rid=rid,
                                 replica=rep.name, emitted=emitted,
                                 error=repr(last_exc))

    def _pick(self, tried, now=None) -> Optional[_Replica]:
        """Power-of-two-choices over routable replicas not yet tried for
        this request: sample two, take the lower load score.  The chosen
        replica is atomically reserved (``try_reserve``) so a half-open
        breaker admits exactly ONE probe even when many dispatcher
        threads race the pick."""
        now = time.monotonic() if now is None else now
        cands = [r for r in self.replicas()
                 if r.name not in tried and r.routable(now)]
        while cands:
            if len(cands) == 1:
                choice = cands[0]
            else:
                with self._lock:
                    a, b = self._rng.sample(cands, 2)
                choice = a if a.score() <= b.score() else b
            if choice.try_reserve():
                return choice
            cands.remove(choice)  # lost the probe-slot race; next best
        return None

    def _call_replica(self, rep: _Replica, req: _Request):
        rep.begin_call()
        t0 = time.monotonic()
        ok = None
        try:
            faults.fire("serving.replica.call")
            faults.fire("serving.replica.%s.call" % rep.name)
            with profiler.Frame("router/call[%s]" % rep.name,
                                category="serving"):
                out = rep.call(req.inputs, req.remaining_ms(), req.rid,
                               req.slo)
            ok = True
            return out
        except DeadlineExceededError:
            raise  # neutral: the budget died, not the replica
        except QueueFullError:
            raise  # neutral: load signal, score/pressure already carry it
        except BaseException:
            ok = False
            raise
        finally:
            rep.end_call(ok, (time.monotonic() - t0) * 1e3)

    def _hedge_delay_s(self) -> Optional[float]:
        if self.hedge_ms == 0:
            return None
        if self.hedge_ms > 0:
            return self.hedge_ms / 1e3
        p99 = self.metrics.latency_quantile(0.99)
        floor = env("MXNET_SERVING_HEDGE_MIN_MS", 5.0, float)
        return max(p99 if p99 is not None else floor, floor) / 1e3

    def _call_hedged(self, rep: _Replica, req: _Request, tried):
        """One attempt, optionally hedged: duplicate onto a second
        replica when the primary is slower than the hedge delay and take
        whichever answers first (same idempotent request id)."""
        delay = self._hedge_delay_s()
        if delay is None:
            return self._call_replica(rep, req)
        primary = self._call_pool.submit(self._call_replica, rep, req)
        try:
            return primary.result(timeout=delay)
        except FutureTimeoutError:
            pass
        except Exception:
            raise
        backup_rep = self._pick(tried)
        if backup_rep is None:
            return primary.result()
        tried.add(backup_rep.name)
        self.metrics.on_hedge()
        faults.fire("serving.router.hedge")
        _telemetry.log_event("router_hedge", rid=req.rid,
                             primary=rep.name, backup=backup_rep.name)
        backup = self._call_pool.submit(self._call_replica, backup_rep, req)
        pending = {primary, backup}
        last_exc = None
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                exc = f.exception()
                if exc is None:
                    if f is backup:
                        self.metrics.on_hedge_win()
                    return f.result()
                last_exc = exc
        raise last_exc

    def _dispatch(self, req: _Request):
        last_exc = None
        tried = set()
        with profiler.Frame("router/dispatch[%s]" % req.slo,
                            category="serving"):
            for attempt in range(self.retries + 1):
                faults.fire("serving.router.dispatch")
                rep = self._pick(tried)
                if rep is None:
                    break
                tried.add(rep.name)
                if attempt:
                    self.metrics.on_retry()
                    _telemetry.log_event(
                        "router_failover", rid=req.rid, to=rep.name,
                        attempt=attempt, error=repr(last_exc))
                try:
                    out = self._call_hedged(rep, req, tried)
                    self.metrics.on_complete(
                        req.slo, (time.monotonic() - req.t0) * 1e3)
                    return out
                except DeadlineExceededError:
                    self.metrics.on_expire(req.slo)
                    raise
                except RouterOverloadError:
                    raise
                except Exception as exc:
                    last_exc = exc
                    continue
        self.metrics.on_fail(req.slo)
        raise NoReplicaAvailableError(
            "request %s failed on every routable replica (tried %s): %r"
            % (req.rid, sorted(tried) or "none", last_exc)) from last_exc

    # -- hot swap ----------------------------------------------------------
    def swap(self, prefix, epoch) -> int:
        """Zero-downtime checkpoint hot-swap, replica by replica.

        For each local replica: build a shadow :class:`InferenceServer`
        from the checkpoint with the replica's own config, warm every
        bucket on it (constructor warmup — steady state never
        recompiles), atomically flip it into rotation, then drain and
        stop the old server.  Requests in flight on the old replica
        finish during the drain; a request that races the flip gets a
        ``ServerClosedError`` from the draining server and is
        transparently retried on another replica — zero failed client
        requests.  Remote replicas swap in place via ``POST /swap``
        (warm-then-flip happens server-side).  Capacity never drops
        below N-1 replicas.  Returns the number of replicas swapped."""
        with self._swap_lock:
            return self._swap_locked(prefix, epoch)

    def _swap_locked(self, prefix, epoch) -> int:
        swapped = 0
        for rep in self.replicas():
            faults.fire("serving.router.swap")
            with profiler.Frame("router/swap[%s]" % rep.name,
                                category="serving"):
                if isinstance(rep, _RemoteReplica):
                    rep.swap(prefix, epoch)
                else:
                    old_srv = rep.server
                    cfg = old_srv.swap_config()
                    shadow = InferenceServer.from_checkpoint(
                        prefix, epoch, cfg.pop("input_shapes"),
                        warmup=True, start=True, **cfg)
                    new_rep = _LocalReplica(rep.name, shadow, self)
                    with self._lock:
                        self._owned.append(shadow)
                        idx = self._replicas.index(rep)
                        self._replicas[idx] = new_rep
                    # drain: in-flight work finishes, the old server then
                    # rejects with ServerClosedError -> router retries
                    old_srv.stop(drain=True)
                    if old_srv in self._owned:
                        self._owned.remove(old_srv)
            swapped += 1
            self.metrics.on_swap()
            _telemetry.log_event("router_swap", replica=rep.name,
                                 prefix=prefix, epoch=int(epoch),
                                 replica_kind=rep.kind)
        self._update_topology_metrics()
        return swapped

    def cold_bucket_runs(self) -> int:
        """Aggregate never-warmed-bucket flush count over the local
        replicas currently in rotation (0 == steady state never
        recompiled)."""
        return sum(r.server.cold_bucket_runs() for r in self.replicas()
                   if isinstance(r, _LocalReplica))

    # -- lifecycle ---------------------------------------------------------
    def close(self, stop_backends: bool = False):
        """Stop dispatching.  Router-owned servers (swap shadows) are
        always drained and stopped; caller-provided backends only with
        ``stop_backends``.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._probe_stop.set()
        self._registry_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
        if self._registry_thread is not None:
            self._registry_thread.join(timeout=5)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._http_thread is not None:
                self._http_thread.join(timeout=5)
                self._http_thread = None
        self._pool.shutdown(wait=True, cancel_futures=True)
        self._call_pool.shutdown(wait=True, cancel_futures=True)
        to_stop = list(self._owned)
        if stop_backends:
            to_stop += [r.server for r in self.replicas()
                        if isinstance(r, _LocalReplica)]
        for srv in to_stop:
            try:
                srv.stop(drain=True)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- HTTP front end ----------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Stdlib HTTP front door in a daemon thread; returns the bound
        ``(host, port)``.

        * ``POST /predict`` — like the InferenceServer endpoint, plus
          ``X-SLO-Class`` / ``X-Request-Id`` / ``X-Deadline-Ms`` headers
          (body fields ``slo`` / ``request_id`` / ``deadline_ms`` win).
          429 + ``Retry-After`` when the class was shed, 503 when no
          replica could serve, 504 past deadline.
        * ``POST /generate`` — ``{"prompt": [ids], "max_new_tokens":
          opt, "deadline_ms": opt, "slo": opt}`` → NDJSON token stream
          (one flushed ``{"token": t}`` line per token, final
          ``{"done": true}``), resumable across replica failures
          (:meth:`generate`); 429 when shed, 503 when no capable
          replica.
        * ``POST /swap`` — ``{"prefix":..., "epoch":N}`` rolls the
          zero-downtime hot-swap across all replicas.
        * ``GET /metrics`` — router Prometheus text.
        * ``GET /healthz`` — router liveness (200 until ``close``).
        * ``GET /readyz`` — 200 when ≥1 replica is routable, else 503.
        * ``GET /replicas`` — JSON state of every replica (breaker state,
          EWMA latency, in-flight, readiness).
        """
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # keep pytest/console output clean
                pass

            def _reply(self, code, body, ctype="application/json",
                       headers=()):
                data = body if isinstance(body, bytes) else body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/metrics":
                    self._reply(200, router.metrics.render_text(),
                                ctype="text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    if router._closed:
                        self._reply(503, json.dumps({"status": "closed"}))
                    else:
                        self._reply(200, "ok", ctype="text/plain")
                elif self.path == "/readyz":
                    now = time.monotonic()
                    n = sum(1 for r in router.replicas() if r.routable(now))
                    if n and not router._closed:
                        self._reply(200, "ready", ctype="text/plain")
                    else:
                        self._reply(503, json.dumps(
                            {"status": "no_ready_replicas"}))
                elif self.path == "/replicas":
                    self._reply(200, json.dumps(router.describe()))
                else:
                    self._reply(404, json.dumps({"error": "not found"}))

            def _generate(self, req):
                slo = req.get("slo") or \
                    self.headers.get("X-SLO-Class") or "generate"
                deadline_ms = req.get("deadline_ms")
                if deadline_ms is None:
                    hdr = self.headers.get("X-Deadline-Ms")
                    if hdr:
                        deadline_ms = float(hdr)
                try:
                    it = router.generate(
                        req.get("prompt", []), req.get("max_new_tokens"),
                        slo=slo, deadline_ms=deadline_ms,
                        request_id=req.get("request_id") or
                        self.headers.get("X-Request-Id"))
                except RouterOverloadError as exc:
                    self._reply(429, json.dumps({"error": str(exc)}),
                                headers=(("Retry-After",
                                          "%g" % exc.retry_after),))
                    return
                except (ServerClosedError, MXNetError) as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Accel-Buffering", "no")
                self.end_headers()
                self.close_connection = True
                n = 0
                try:
                    for tok in it:
                        self.wfile.write(
                            (json.dumps({"token": int(tok)}) + "\n")
                            .encode())
                        self.wfile.flush()
                        n += 1
                    self.wfile.write((json.dumps(
                        {"done": True, "n": n}) + "\n").encode())
                    self.wfile.flush()
                except BrokenPipeError:
                    it.close()  # client went away: stop the stream
                except BaseException as exc:
                    try:
                        self.wfile.write((json.dumps(
                            {"error": repr(exc)}) + "\n").encode())
                        self.wfile.flush()
                    except OSError:
                        pass

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/generate":
                        self._generate(req)
                        return
                    if self.path == "/swap":
                        swapped = router.swap(req["prefix"],
                                              int(req["epoch"]))
                        self._reply(200, json.dumps({"swapped": swapped}))
                        return
                    if self.path != "/predict":
                        self._reply(404, json.dumps({"error": "not found"}))
                        return
                    slo = req.get("slo") or \
                        self.headers.get("X-SLO-Class") or "interactive"
                    deadline_ms = req.get("deadline_ms")
                    if deadline_ms is None:
                        hdr = self.headers.get("X-Deadline-Ms")
                        if hdr:
                            deadline_ms = float(hdr)
                    rid = req.get("request_id") or \
                        self.headers.get("X-Request-Id")
                    fut = router.submit(slo=slo, deadline_ms=deadline_ms,
                                        request_id=rid,
                                        **req.get("inputs", {}))
                    outs = fut.result()
                    self._reply(200, json.dumps(
                        {"outputs": [np.asarray(o).tolist()
                                     for o in outs]}))
                except RouterOverloadError as exc:
                    self._reply(429, json.dumps({"error": str(exc)}),
                                headers=(("Retry-After",
                                          "%g" % exc.retry_after),))
                except DeadlineExceededError as exc:
                    self._reply(504, json.dumps({"error": str(exc)}))
                except (NoReplicaAvailableError, ServerClosedError,
                        QueueFullError) as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                except (MXNetError, ValueError, TypeError, KeyError,
                        OSError, json.JSONDecodeError) as exc:
                    self._reply(400, json.dumps({"error": repr(exc)}))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-router-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address

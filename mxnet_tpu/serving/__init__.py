"""mxnet_tpu.serving — dynamic-batching inference service over Predictor.

The production serving tier (docs/how_to/serving.md): a request queue +
micro-batcher that coalesces concurrent traffic into padded power-of-two
bucket batches (pre-compiled at startup, so steady state never
recompiles), a threaded front end with futures / bounded-queue
backpressure / per-request deadlines / graceful drain, an optional
stdlib-HTTP endpoint, Prometheus-style metrics wired into the
chrome-trace profiler — and, over N such replicas, a resilient
:class:`Router` front door with health/load-aware dispatch, per-replica
circuit breakers, bounded retry + hedging, per-SLO admission classes,
and zero-downtime checkpoint hot-swap.
"""
from .autoscaler import Autoscaler, LocalCheckpointProvider, ProcessProvider
from .batcher import (BucketedPredictor, DeadlineExceededError,
                      DrainTimeoutError, MicroBatcher, QueueFullError,
                      ServerClosedError, pow2_buckets)
from .metrics import ServingMetrics
from .registry import ReplicaRegistry, RegistryClient, start_heartbeater
from .router import (NoReplicaAvailableError, Router, RouterError,
                     RouterMetrics, RouterOverloadError, SLOClass)
from .server import InferenceServer, install_preemption_handler

__all__ = ["InferenceServer", "BucketedPredictor", "MicroBatcher",
           "ServingMetrics", "pow2_buckets", "QueueFullError",
           "DeadlineExceededError", "ServerClosedError",
           "DrainTimeoutError",
           "Router", "SLOClass", "RouterMetrics", "RouterError",
           "NoReplicaAvailableError", "RouterOverloadError",
           "ReplicaRegistry", "RegistryClient", "start_heartbeater",
           "Autoscaler", "LocalCheckpointProvider", "ProcessProvider",
           "install_preemption_handler"]

"""ReplicaRegistry — the shared live-set behind router replication.

A single :class:`~mxnet_tpu.serving.router.Router` is itself a single
point of failure: kill the front door and every client loses the fleet,
even though the replicas behind it are fine.  The fix is the same one
the elastic kvstore applied to training workers (PR 6's membership
table): replicas **register** into a shared table with monotonic
generations, **heartbeat** to stay live, and are **evicted** on stale
heartbeats — and N stateless routers watching that table converge on
the same live set, so any router can serve any request and killing one
mid-load loses nothing.

This module is that membership-table machinery re-hosted at the serving
layer (same contract as ``KVStoreServer``'s join/leave/evict/membership
RPCs: a generation counter bumped on every change lets a poller detect
churn with one integer compare; stale-heartbeat eviction turns kill -9
into a membership event instead of a hang).  Members are keyed by name
and carry a backend — either a ``host:port`` string (cross-process) or
a live in-process object such as an :class:`InferenceServer` (the chaos
scenarios run whole fleets in one process).

Three faces:

* :class:`ReplicaRegistry` — the table itself, embeddable in-process.
* ``ReplicaRegistry.serve_http()`` — the same table as a stdlib HTTP
  service (``POST /register|/heartbeat|/deregister``,
  ``GET /replicas|/healthz``) for multi-process fleets.
* :class:`RegistryClient` — the HTTP face re-exposed under the same
  method signatures, so routers and replicas take either one.

Registry I/O is a ``faults`` dotted op (``serving.registry.call``) so
chaos runs can partition a router from the registry deterministically.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env

__all__ = ["ReplicaRegistry", "RegistryClient", "start_heartbeater"]

register_env("MXNET_SERVING_REGISTRY_TTL_MS", 3000.0, float,
             "Heartbeat staleness budget: a registered serving replica "
             "(or router) silent for longer is evicted from the live "
             "set, exactly like the kvstore membership table's "
             "MXNET_KVSTORE_EVICT_TIMEOUT.")
register_env("MXNET_SERVING_REGISTRY_HEARTBEAT_MS", 1000.0, float,
             "Period of a registered replica's keep-alive heartbeats to "
             "the replica registry.")


class ReplicaRegistry:
    """Name -> backend live-set with generations and stale eviction.

    ``gen`` is bumped on every register/deregister/evict, never on a
    heartbeat, so a router syncing against the registry re-reads the
    member list only when it actually changed.  Eviction is lazy (every
    read sweeps stale members first) — no background thread to leak, and
    a table nobody reads costs nothing.
    """

    def __init__(self, ttl_ms: Optional[float] = None):
        self._ttl_s = (env("MXNET_SERVING_REGISTRY_TTL_MS", 3000.0, float)
                       if ttl_ms is None else float(ttl_ms)) / 1e3
        self._lock = threading.Lock()
        self._members: Dict[str, dict] = {}  # name -> record
        self._gen = 0
        self._httpd = None
        self._http_thread = None

    # -- membership --------------------------------------------------------
    def register(self, name: str, backend, meta: Optional[dict] = None):
        """Admit (or refresh) a member; returns the new generation."""
        if not name:
            raise MXNetError("registry member needs a non-empty name")
        with self._lock:
            fresh = name not in self._members
            self._members[name] = {
                "backend": backend,
                "meta": dict(meta or {}),
                "beat": time.monotonic(),
            }
            if fresh:
                self._gen += 1
            gen = self._gen
        _telemetry.log_event("serving_registry", op="register", name=name,
                             gen=gen)
        return gen

    def heartbeat(self, name: str) -> bool:
        """Refresh one member's liveness; False when it is not (or no
        longer) a member — the signal a replica uses to re-register after
        an eviction it slept through."""
        with self._lock:
            rec = self._members.get(name)
            if rec is None:
                return False
            rec["beat"] = time.monotonic()
            return True

    def deregister(self, name: str):
        """Graceful leave; returns the new generation (unchanged when the
        member was already gone)."""
        with self._lock:
            if self._members.pop(name, None) is not None:
                self._gen += 1
            gen = self._gen
        _telemetry.log_event("serving_registry", op="deregister", name=name,
                             gen=gen)
        return gen

    def _evict_stale_locked(self):
        now = time.monotonic()
        stale = [n for n, rec in self._members.items()
                 if now - rec["beat"] > self._ttl_s]
        for n in stale:
            del self._members[n]
            self._gen += 1
        return stale

    def live(self) -> dict:
        """``{"gen": G, "replicas": {name: backend}, "meta": {name:
        dict}}`` after sweeping stale members (the poll every router
        syncs against).  ``meta`` is additive — pre-platform consumers
        that only read ``replicas`` keep working, and a member that
        registered without meta shows an empty dict (the default-model
        convention the per-model router filter relies on)."""
        with self._lock:
            stale = self._evict_stale_locked()
            out = {"gen": self._gen,
                   "replicas": {n: rec["backend"]
                                for n, rec in self._members.items()},
                   "meta": {n: dict(rec["meta"])
                            for n, rec in self._members.items()}}
        for n in stale:
            _telemetry.log_event("serving_registry", op="evict", name=n,
                                 gen=out["gen"])
        return out

    def gen(self) -> int:
        with self._lock:
            self._evict_stale_locked()
            return self._gen

    # -- HTTP face ---------------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Expose the table as a stdlib HTTP service; returns the bound
        ``(host, port)``.  Backends must be ``host:port`` strings in this
        mode (an in-process object cannot cross the wire)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/replicas":
                    self._reply(200, registry.live())
                elif self.path == "/healthz":
                    self._reply(200, {"status": "ok",
                                      "gen": registry.gen()})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    name = req.get("name", "")
                    if self.path == "/register":
                        backend = req["backend"]
                        if not isinstance(backend, str):
                            raise MXNetError(
                                "HTTP registry backends must be host:port "
                                "strings")
                        gen = registry.register(name, backend,
                                                req.get("meta"))
                        self._reply(200, {"gen": gen})
                    elif self.path == "/heartbeat":
                        self._reply(200, {"ok": registry.heartbeat(name)})
                    elif self.path == "/deregister":
                        self._reply(200, {"gen": registry.deregister(name)})
                    else:
                        self._reply(404, {"error": "not found"})
                except (MXNetError, ValueError, TypeError, KeyError,
                        json.JSONDecodeError) as exc:
                    self._reply(400, {"error": repr(exc)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-registry-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address

    @property
    def addr(self) -> str:
        if self._httpd is None:
            raise MXNetError("registry is not serving HTTP")
        host, port = self._httpd.server_address[:2]
        return "%s:%d" % (host, port)

    def close(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._http_thread is not None:
                self._http_thread.join(timeout=5)
                self._http_thread = None


class RegistryClient:
    """HTTP client with the same surface as :class:`ReplicaRegistry`, so
    a router or replica takes either without caring which process hosts
    the table."""

    def __init__(self, addr: str, timeout: float = 2.0):
        self.addr = addr
        self._base = "http://%s" % addr
        self._timeout = timeout

    def _post(self, path, payload):
        import urllib.request

        faults.fire("serving.registry.call")
        req = urllib.request.Request(
            self._base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            return json.loads(resp.read())

    def _get(self, path):
        import urllib.request

        faults.fire("serving.registry.call")
        with urllib.request.urlopen(self._base + path,
                                    timeout=self._timeout) as resp:
            return json.loads(resp.read())

    def register(self, name, backend, meta=None):
        return self._post("/register", {"name": name, "backend": backend,
                                        "meta": meta or {}})["gen"]

    def heartbeat(self, name) -> bool:
        return bool(self._post("/heartbeat", {"name": name})["ok"])

    def deregister(self, name):
        return self._post("/deregister", {"name": name})["gen"]

    def live(self) -> dict:
        return self._get("/replicas")

    def gen(self) -> int:
        return self._get("/healthz")["gen"]


def start_heartbeater(registry, name: str, backend,
                      interval_ms: Optional[float] = None,
                      meta: Optional[dict] = None):
    """Register ``name`` and keep it alive with background heartbeats
    (re-registering after any eviction/registry restart — the member,
    not the table, owns its liveness).  Returns a ``stop()`` callable
    that deregisters and joins the thread; used by serving replicas and
    by replicated routers alike."""
    interval_s = (env("MXNET_SERVING_REGISTRY_HEARTBEAT_MS", 1000.0, float)
                  if interval_ms is None else float(interval_ms)) / 1e3
    registry.register(name, backend, meta)
    stop_evt = threading.Event()

    def loop():
        while not stop_evt.wait(interval_s):
            try:
                if not registry.heartbeat(name):
                    registry.register(name, backend, meta)
            except Exception:
                pass  # registry blip: keep beating, it may come back

    thread = threading.Thread(target=loop, name="mxtpu-registry-beat",
                              daemon=True)
    thread.start()

    def stop(deregister: bool = True):
        stop_evt.set()
        thread.join(timeout=5)
        if deregister:
            try:
                registry.deregister(name)
            except Exception:
                pass

    return stop

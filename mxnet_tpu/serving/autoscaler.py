"""Autoscaler — the control loop that closes the serving feedback loop.

The Router (PR 8) *detects* trouble — queue pressure, per-SLO p99 vs
deadline budget, breaker-open count, shed rate — and PR 10 made
replicas cheap to start warm (AOT bundles + compile cache), but replica
count stayed static.  This module is the missing controller: a small
loop over :meth:`Router.signals` that holds the non-draining replica
count inside a ``MIN:MAX`` band.

Control law (deliberately boring — serving controllers should be):

* **overloaded** when aggregate pressure crosses
  ``MXNET_SERVING_AUTOSCALE_OUT_PRESSURE``, any SLO class's p99 exceeds
  its deadline budget, requests were shed since the last tick, or a
  breaker is open (an open breaker is lost capacity, not just noise).
* **underloaded** when pressure is below
  ``MXNET_SERVING_AUTOSCALE_IN_PRESSURE`` and none of the overload
  signals fire.
* **hysteresis**: a direction must hold for
  ``MXNET_SERVING_AUTOSCALE_HYSTERESIS`` consecutive ticks before it
  actuates — one hot tick must not spawn a replica.
* **cooldown**: after any scale event, decisions pause for
  ``MXNET_SERVING_AUTOSCALE_COOLDOWN_MS`` so the fleet's response to
  the last action is measured before the next one (no flapping).

Scale-out asks a *provider* for a warm replica (AOT/compile-cache
attach — the first request on a fresh replica must run with
``cold_bucket_runs() == 0``).  Scale-in picks the least-loaded replica
the autoscaler itself spawned, flips it to draining (``/readyz`` 503,
no new dispatch), waits for inflight under the hard
``MXNET_SERVING_DRAIN_TIMEOUT_MS`` deadline, then retires it.  Every
decision is a structured telemetry event and a fault-injectable dotted
op (``serving.autoscaler.scale_out`` / ``scale_in`` / ``drain``), and
the clock is injectable so hysteresis/cooldown are unit-testable
without a single real sleep.

Providers::

    LocalCheckpointProvider   # in-process InferenceServer per spawn
    ProcessProvider           # one OS process per spawn (launch.py
                              # serving actuator); retires via SIGTERM,
                              # sharing the preemption drain path

A provider with ``self_registering=True`` (anything given a registry)
announces its replicas through the :class:`ReplicaRegistry`; replicated
routers discover them via their sync loop and the autoscaler never
touches ``add_replica`` directly — the registry stays the single source
of fleet truth.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from .registry import start_heartbeater
from .server import InferenceServer

__all__ = ["Autoscaler", "LocalCheckpointProvider", "ProcessProvider"]

register_env("MXNET_SERVING_AUTOSCALE_MIN", 1, int,
             "Autoscaler floor: never drain below this many serving "
             "replicas.")
register_env("MXNET_SERVING_AUTOSCALE_MAX", 4, int,
             "Autoscaler ceiling: never spawn above this many serving "
             "replicas.")
register_env("MXNET_SERVING_AUTOSCALE_INTERVAL_MS", 500.0, float,
             "Autoscaler control-loop tick period.")
register_env("MXNET_SERVING_AUTOSCALE_OUT_PRESSURE", 0.5, float,
             "Aggregate queue pressure (backlog/capacity) at or above "
             "which a tick votes scale-out.")
register_env("MXNET_SERVING_AUTOSCALE_IN_PRESSURE", 0.1, float,
             "Aggregate queue pressure at or below which a tick votes "
             "scale-in (only when no overload signal fires).")
register_env("MXNET_SERVING_AUTOSCALE_HYSTERESIS", 2, int,
             "Consecutive same-direction autoscaler ticks required "
             "before a scale decision actuates.")
register_env("MXNET_SERVING_AUTOSCALE_COOLDOWN_MS", 5000.0, float,
             "Pause after any scale event before the autoscaler makes "
             "another decision (anti-flap).")


class Autoscaler:
    """Pressure/SLO-driven replica-count controller over one Router.

    Parameters
    ----------
    router : Router
        Source of :meth:`~Router.signals` and (for non-registry
        providers) the actuation target.
    provider
        ``spawn() -> (name, backend)`` / ``retire(name, backend)``; see
        :class:`LocalCheckpointProvider`.  ``self_registering`` providers
        announce replicas via the registry instead of the router.
    min_replicas, max_replicas : int
        The band; defaults from ``MXNET_SERVING_AUTOSCALE_MIN/_MAX``.
    clock : callable
        Monotonic-seconds source; tests inject a fake one so hysteresis
        and cooldown are exercised without real sleeps.
    """

    def __init__(self, router, provider,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 interval_ms: Optional[float] = None,
                 out_pressure: Optional[float] = None,
                 in_pressure: Optional[float] = None,
                 hysteresis: Optional[int] = None,
                 cooldown_ms: Optional[float] = None,
                 drain_timeout_ms: Optional[float] = None,
                 clock=time.monotonic):
        def knob(val, name, default, typ):
            return env(name, default, typ) if val is None else typ(val)

        self._router = router
        self._provider = provider
        self._min = knob(min_replicas, "MXNET_SERVING_AUTOSCALE_MIN", 1, int)
        self._max = knob(max_replicas, "MXNET_SERVING_AUTOSCALE_MAX", 4, int)
        if not 1 <= self._min <= self._max:
            raise MXNetError("bad autoscale band %d:%d"
                             % (self._min, self._max))
        self._interval_s = knob(interval_ms,
                                "MXNET_SERVING_AUTOSCALE_INTERVAL_MS",
                                500.0, float) / 1e3
        self._out_pressure = knob(out_pressure,
                                  "MXNET_SERVING_AUTOSCALE_OUT_PRESSURE",
                                  0.5, float)
        self._in_pressure = knob(in_pressure,
                                 "MXNET_SERVING_AUTOSCALE_IN_PRESSURE",
                                 0.1, float)
        self._hyst = max(1, knob(hysteresis,
                                 "MXNET_SERVING_AUTOSCALE_HYSTERESIS",
                                 2, int))
        self._cooldown_s = knob(cooldown_ms,
                                "MXNET_SERVING_AUTOSCALE_COOLDOWN_MS",
                                5000.0, float) / 1e3
        self._drain_timeout_ms = drain_timeout_ms
        self._clock = clock
        self._over = 0
        self._under = 0
        self._last_event = None  # clock() of the last actuation
        self._last_shed = None
        self._owned = {}  # name -> backend (replicas this loop spawned)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.events = []  # decision log (tests + bench read this)
        reg = self._registry = _telemetry.Registry()
        self._c_out = reg.counter("mxtpu_autoscale_out_total",
                                  "Scale-out actuations.")
        self._c_in = reg.counter("mxtpu_autoscale_in_total",
                                 "Scale-in actuations.")
        self._c_failed = reg.counter("mxtpu_autoscale_failed_total",
                                     "Scale actuations that raised.")
        self._g_owned = reg.gauge("mxtpu_autoscale_owned_replicas",
                                  "Replicas this autoscaler spawned and "
                                  "still owns.")

    # -- signals -> decision ------------------------------------------------
    def _classify(self, sig) -> str:
        """One tick's vote: ``out`` / ``in`` / ``hold`` plus why."""
        shed = sig["shed_total"]
        shed_delta = 0 if self._last_shed is None else shed - self._last_shed
        self._last_shed = shed
        slo_hot = [s for s, v in sig["p99_ms"].items()
                   if v > sig["deadline_ms"][s]]
        reasons = []
        if sig["pressure"] >= self._out_pressure:
            reasons.append("pressure=%.2f" % sig["pressure"])
        if slo_hot:
            reasons.append("slo_p99_over_budget=%s" % ",".join(slo_hot))
        if shed_delta > 0:
            reasons.append("shed_delta=%d" % shed_delta)
        if sig["breakers_open"] > 0:
            reasons.append("breakers_open=%d" % sig["breakers_open"])
        if reasons:
            return "out", ";".join(reasons)
        if sig["pressure"] <= self._in_pressure:
            return "in", "pressure=%.2f" % sig["pressure"]
        return "hold", ""

    def tick(self) -> Optional[dict]:
        """One control-loop iteration; returns the decision event when a
        scale actuation happened, else None.  Pure function of the
        router's signals + the injected clock — the whole hysteresis /
        cooldown state machine runs through here."""
        now = self._clock()
        sig = self._router.signals()
        vote, why = self._classify(sig)
        if vote == "out":
            self._over += 1
            self._under = 0
        elif vote == "in":
            self._under += 1
            self._over = 0
        else:
            self._over = self._under = 0
        if (self._last_event is not None
                and now - self._last_event < self._cooldown_s):
            return None  # cooling down: observe, don't actuate
        active = sig["replicas"] - sig["draining"]
        if vote == "out" and self._over >= self._hyst and active < self._max:
            return self._scale_out(now, sig, why)
        if vote == "in" and self._under >= self._hyst and active > self._min:
            return self._scale_in(now, sig, why)
        return None

    # -- actuation ----------------------------------------------------------
    def _record(self, event):
        self.events.append(event)
        _telemetry.log_event("autoscale", **event)
        return event

    def _scale_out(self, now, sig, why):
        self._over = 0
        self._last_event = now
        try:
            faults.fire("serving.autoscaler.scale_out")
            name, backend = self._provider.spawn()
            if not getattr(self._provider, "self_registering", False):
                self._router.add_replica(backend, name=name)
        except Exception as exc:
            self._c_failed.inc()
            return self._record({"op": "scale_out", "ok": False,
                                 "why": why, "error": repr(exc)})
        with self._lock:
            self._owned[name] = backend
            self._g_owned.set(len(self._owned))
        self._c_out.inc()
        return self._record({"op": "scale_out", "ok": True, "replica": name,
                             "why": why, "replicas": sig["replicas"] + 1,
                             "pressure": round(sig["pressure"], 3)})

    def _pick_victim(self):
        """Least-loaded non-draining replica among the ones this loop
        spawned — the seed fleet (anything it did not spawn) is never
        retired, so the MIN band and the operator's baseline both hold."""
        with self._lock:
            owned = set(self._owned)
        cands = [d for d in self._router.describe()
                 if d["name"] in owned and not d["draining"]]
        if not cands:
            return None
        return min(cands,
                   key=lambda d: (d["inflight"] + d["queue_depth"],
                                  d["name"]))["name"]

    def _scale_in(self, now, sig, why):
        victim = self._pick_victim()
        if victim is None:
            return None  # nothing we own is retirable; keep observing
        self._under = 0
        self._last_event = now
        with self._lock:
            backend = self._owned.pop(victim)
            self._g_owned.set(len(self._owned))
        try:
            faults.fire("serving.autoscaler.scale_in")
            faults.fire("serving.autoscaler.drain")
            if getattr(self._provider, "self_registering", False):
                # deregistration is the announcement; every replicated
                # router drain-removes it through its registry sync
                self._provider.retire(victim, backend)
            else:
                self._router.remove_replica(
                    victim, drain=True,
                    drain_timeout_ms=self._drain_timeout_ms)
                self._provider.retire(victim, backend)
        except Exception as exc:
            self._c_failed.inc()
            return self._record({"op": "scale_in", "ok": False,
                                 "replica": victim, "why": why,
                                 "error": repr(exc)})
        self._c_in.inc()
        return self._record({"op": "scale_in", "ok": True, "replica": victim,
                             "why": why, "replicas": sig["replicas"] - 1,
                             "pressure": round(sig["pressure"], 3)})

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        """Run :meth:`tick` every interval in a daemon thread."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._interval_s):
                try:
                    self.tick()
                except Exception:
                    self._c_failed.inc()

        self._thread = threading.Thread(target=loop,
                                        name="mxtpu-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, retire_owned: bool = False):
        """Stop the loop; with ``retire_owned`` also drain-retire every
        replica this autoscaler spawned (test/bench teardown)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if retire_owned:
            with self._lock:
                owned = dict(self._owned)
                self._owned.clear()
                self._g_owned.set(0)
            for name, backend in owned.items():
                try:
                    if not getattr(self._provider, "self_registering",
                                   False):
                        self._router.remove_replica(
                            name, drain=True,
                            drain_timeout_ms=self._drain_timeout_ms)
                    self._provider.retire(name, backend)
                except Exception:
                    pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(retire_owned=True)

    def owned(self):
        with self._lock:
            return dict(self._owned)

    def metrics_text(self):
        return self._registry.render_prometheus()


class LocalCheckpointProvider:
    """Spawn warm in-process :class:`InferenceServer` replicas from one
    checkpoint prefix.

    With ``attach_aot`` (default) each spawn attaches the checkpoint's
    AOT bundle / compile cache before warmup, so every bucket warms by
    deserializing its executable — the scaled-out replica's first
    request runs with ``cold_bucket_runs() == 0``.  Given a
    ``registry``, each spawn registers + heartbeats there
    (``self_registering``); replicated routers pick it up via sync.
    """

    def __init__(self, prefix, epoch, input_shapes, registry=None,
                 attach_aot: bool = True, name_prefix: str = "auto",
                 meta=None, **server_kwargs):
        self._prefix = prefix
        self._epoch = int(epoch)
        self._input_shapes = dict(input_shapes)
        self._registry = registry
        self._attach_aot = bool(attach_aot)
        self._name_prefix = name_prefix
        # registration meta (e.g. {"model": ..., "tenant": ...}) so
        # model-scoped routers adopt only this provider's replicas
        self._meta = dict(meta) if meta else None
        self._server_kwargs = dict(server_kwargs)
        self._seq = itertools.count()
        self._beat_stops = {}

    @property
    def self_registering(self) -> bool:
        return self._registry is not None

    def spawn(self):
        name = "%s%d" % (self._name_prefix, next(self._seq))
        server = InferenceServer.from_checkpoint(
            self._prefix, self._epoch, self._input_shapes,
            attach_aot=self._attach_aot, **self._server_kwargs)
        if self._registry is not None:
            self._beat_stops[name] = start_heartbeater(
                self._registry, name, server, meta=self._meta)
        return name, server

    def retire(self, name, server):
        server.begin_drain()  # /readyz 503: no router dispatches here again
        stop_beat = self._beat_stops.pop(name, None)
        if stop_beat is not None:
            stop_beat()  # deregisters; router syncs drain-remove it
        server.stop(drain=True)


class ProcessProvider:
    """Spawn one OS process per replica through the ``launch.py``
    serving actuator.  Always ``self_registering``: the child process
    registers itself (name passed via ``--name``) against the registry
    HTTP address and installs the SIGTERM preemption handler, so
    ``retire`` is just SIGTERM — autoscaler retirement and cluster
    preemption run the identical drain → deregister → postmortem path.
    """

    self_registering = True

    def __init__(self, registry_addr: str, prefix, epoch, input_shapes,
                 name_prefix: str = "proc", extra_args=()):
        self._registry_addr = registry_addr
        self._prefix = prefix
        self._epoch = int(epoch)
        self._input_shapes = dict(input_shapes)
        self._name_prefix = name_prefix
        self._extra_args = list(extra_args)
        self._seq = itertools.count()

    def spawn(self):
        import json
        import os
        import subprocess
        import sys

        name = "%s%d" % (self._name_prefix, next(self._seq))
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cmd = [sys.executable, os.path.join(here, "tools", "launch.py"),
               "--serving", "--registry", self._registry_addr,
               "--name", name,
               "--prefix", str(self._prefix), "--epoch", str(self._epoch),
               "--input-shapes",
               json.dumps({k: list(v)
                           for k, v in self._input_shapes.items()}),
               ] + self._extra_args
        proc = subprocess.Popen(cmd)
        return name, proc

    def retire(self, name, proc):
        import signal as _signal

        proc.send_signal(_signal.SIGTERM)
        try:
            proc.wait(timeout=env("MXNET_SERVING_DRAIN_TIMEOUT_MS",
                                  30000.0, float) / 1e3 + 10)
        except Exception:
            proc.kill()

"""InferenceServer — the threaded serving front end over the micro-batcher.

``submit()`` gives a ``concurrent.futures.Future`` per request (the
in-process RPC surface); ``serve_http()`` optionally exposes the same
thing as a small stdlib HTTP endpoint (JSON in/out, ``/metrics`` in
Prometheus text format) so a converted checkpoint becomes a network
service with zero extra dependencies.  Admission control is a bounded
queue: beyond ``max_queue`` pending requests, ``submit`` raises
:class:`QueueFullError` (HTTP 503) instead of letting latency grow
without bound — callers retry with backoff, which is the backpressure
contract.
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError, env, register_env
from ..context import Context
from .batcher import (BucketedPredictor, DeadlineExceededError, MicroBatcher,
                      QueueFullError, ServerClosedError, pow2_buckets)
from .metrics import ServingMetrics

__all__ = ["InferenceServer", "install_preemption_handler"]

register_env("MXNET_SERVING_MAX_WAIT_US", 2000, int,
             "Default micro-batch flush deadline for InferenceServer.")
register_env("MXNET_SERVING_MAX_QUEUE", 256, int,
             "Default admission-control queue bound for InferenceServer.")
register_env("MXNET_SERVING_DRAIN_TIMEOUT_MS", 30000.0, float,
             "Hard deadline for a draining InferenceServer stop: past it, "
             "still-pending requests are force-cancelled with "
             "DrainTimeoutError instead of letting a wedged batch worker "
             "hang retirement forever.")


def _autotune_buckets(max_batch):
    """Tuned micro-batch bucket ladder for ``max_batch``, or None.

    Analytic objective: expected relative padding waste under uniform
    1..max_batch batch demand, plus a per-bucket penalty — every bucket
    is one more executable to compile, warm, and keep resident."""
    try:
        from .. import autotune
    except Exception:
        return None
    if not autotune.enabled():
        return None
    mb = int(max_batch)

    def score(cand):
        buckets = sorted(int(b) for b in cand["buckets"])
        waste = 0.0
        for n in range(1, mb + 1):
            b = next((b for b in buckets if b >= n), buckets[-1])
            waste += (b - n) / float(b)
        return waste / mb + 0.03 * len(buckets)

    try:
        cfg = autotune.get_or_tune(
            "serving_buckets", {"max_batch": mb},
            candidates=autotune.spaces.serving_buckets(mb),
            score_fn=score, default=None)
    except Exception:
        return None
    return list(cfg["buckets"]) if cfg else None


class InferenceServer:
    """Dynamic-batching inference service over a (symbol, params) checkpoint.

    Parameters
    ----------
    symbol, params, dtype
        As for :class:`mxnet_tpu.Predictor`.
    input_shapes : dict
        ``{input_name: shape}`` INCLUDING the leading batch axis; the
        leading dim of the first input is the default ``max_batch_size``
        and per-request inputs carry the remaining dims.
    ctx : Context | list of Context, optional
        One replica (bucket-predictor family + worker thread) is built
        per context, all pulling from one shared queue.
    buckets : sequence of int, optional
        Allowed padded batch sizes; default ``pow2_buckets(max_batch)``.
    max_wait_us : int
        Flush deadline: a queued request never waits longer than this for
        its batch to fill.
    max_queue : int
        Admission bound; ``submit`` beyond it raises ``QueueFullError``.
    warmup : bool
        Pre-compile every bucket before accepting traffic (default True).
    """

    def __init__(self, symbol, params, input_shapes: Dict[str, Sequence[int]],
                 ctx=None, buckets: Optional[Sequence[int]] = None,
                 max_wait_us: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 dtype=np.float32, warmup: bool = True, start: bool = True,
                 generator_spec: Optional[Dict] = None):
        shapes = {k: tuple(v) for k, v in input_shapes.items()}
        batch_dims = {s[0] for s in shapes.values() if len(s) >= 1}
        if len(batch_dims) != 1:
            raise MXNetError(
                "all serving inputs must share one leading batch dim, got %s"
                % shapes)
        max_batch = batch_dims.pop()
        if buckets is None:
            tuned = _autotune_buckets(max_batch)
            buckets = (tuned if tuned is not None
                       else pow2_buckets(max_batch))
        self._item_shapes = {k: s[1:] for k, s in shapes.items()}
        self._input_shapes = shapes
        self._dtype = np.dtype(dtype)
        ctxs = ctx if isinstance(ctx, (list, tuple)) else [ctx]
        self._ctxs = list(ctxs)
        # release-relevant state BEFORE any device allocation, so a
        # mid-construction failure can unwind whatever was built
        self._replicas = []
        self._batcher = None
        self._generator = None
        self._generator_spec = None
        self._model_params = params
        self._released_cold_runs = 0
        self._httpd = None
        self._http_thread = None
        # lifecycle for the liveness/readiness split: readiness is gated
        # on started + warmed + not draining/stopped, liveness (healthz)
        # keeps its worker-thread semantics untouched
        self._started = False
        self._draining = False
        self._stopped = False
        self._swap_lock = threading.Lock()
        try:
            self._replicas = [
                BucketedPredictor(symbol, params, self._item_shapes,
                                  buckets, ctx=c, dtype=dtype)
                for c in ctxs]
            self.buckets = self._replicas[0].buckets
            self.metrics = ServingMetrics()
            self._batcher = MicroBatcher(
                self._replicas, self.metrics,
                max_wait_us=env("MXNET_SERVING_MAX_WAIT_US", 2000, int)
                if max_wait_us is None else max_wait_us,
                max_queue=env("MXNET_SERVING_MAX_QUEUE", 256, int)
                if max_queue is None else max_queue)
            # snapshots that must survive a post-stop release (swap_config
            # and the router's capacity estimate read these, possibly on a
            # server whose predictors were already dropped by page-out)
            self._max_wait_us = self._batcher.max_wait_us
            self._max_queue = self._batcher.max_queue
            # generative sidecar: a DecodeEngine sharing this checkpoint's
            # params, driving POST /generate token streaming
            if generator_spec is not None:
                from ..generation import DecodeEngine

                self.attach_generator(DecodeEngine(
                    params, warmup=warmup, start=start, ctx=self._ctxs[0],
                    dtype=dtype, **generator_spec))
            # warmup=False is an explicit opt-out (lazy compiles): the
            # server counts as warmed-for-readiness the moment it starts
            self._warmed = not warmup
            if warmup:
                self.warmup()
            if start:
                self.start()
        except BaseException:
            self._abort_partial_build()
            raise

    def _abort_partial_build(self):
        """Unwind a construction that failed midway (a torn AOT bundle, a
        fault-injected warmup IOError): stop whatever threads already run
        and drop every device-memory reference, so the failed attempt pins
        nothing — ``resident_bytes()`` of the owner returns to its
        pre-attempt value instead of leaking a half-built replica through
        a live DecodeEngine loop thread."""
        self._stopped = True
        self._draining = True
        gen = self._generator
        if gen is not None:
            try:
                gen.stop(drain=False, timeout=5.0)
            except Exception:
                pass
        batcher = self._batcher
        if batcher is not None:
            try:
                batcher.stop(drain=False, timeout=5.0)
                batcher.release()
            except Exception:
                pass
        self._replicas = []
        self._generator = None
        self._model_params = None

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, attach_aot=True,
                        **kwargs):
        """Serve ``save_checkpoint`` files directly (the file pair
        ``Predictor.from_checkpoint`` consumes).

        When an AOT bundle (``prefix-NNNN.aot/``, written by
        :meth:`save_aot_bundle`) sits beside the params and
        ``attach_aot`` is True it is attached as a read-only
        compile-cache overlay BEFORE warmup, so every bucket warms by
        deserializing its executable instead of compiling it.  A bundle
        built for a different device topology raises
        :class:`MXNetError` (pass ``attach_aot=False`` to serve without
        it).  A bundle whose warmup manifest records a generator spec
        restores the :class:`~mxnet_tpu.generation.DecodeEngine` too —
        its prefill/decode executables warm deserialize-only alongside
        the scoring buckets (pass an explicit ``generator_spec`` to
        override)."""
        if attach_aot:
            from ..checkpoint import attach_aot_bundle

            manifest = attach_aot_bundle(prefix, epoch)
            gen_spec = ((manifest or {}).get("warmup") or {}) \
                .get("generator")
            if gen_spec and "generator_spec" not in kwargs:
                kwargs["generator_spec"] = gen_spec
        return cls("%s-symbol.json" % prefix,
                   "%s-%04d.params" % (prefix, epoch),
                   input_shapes, **kwargs)

    def attach_generator(self, engine):
        """Attach a :class:`~mxnet_tpu.generation.DecodeEngine` (usually
        built by the ``generator_spec`` ctor kwarg) so this server answers
        ``POST /generate`` with streamed tokens.  The engine's compiled
        executables ride along in :meth:`compiled_entries` /
        :meth:`save_aot_bundle`, its spec in :meth:`swap_config`, and
        :meth:`swap` rebuilds it on the new params."""
        self._generator = engine
        self._generator_spec = engine.spec()
        return self

    def submit_generate(self, prompt, max_new_tokens=None,
                        deadline_ms=None):
        """Queue one generation request; returns its
        :class:`~mxnet_tpu.generation.GenStream` (iterate for tokens).
        Raises ``QueueFullError`` on admission rejection (HTTP 429) and
        :class:`MXNetError` when no generator is attached."""
        if self._generator is None:
            raise MXNetError(
                "no generator attached — construct InferenceServer with "
                "generator_spec= or call attach_generator()")
        if self._stopped:
            raise ServerClosedError("server is stopped")
        return self._generator.submit(prompt, max_new_tokens,
                                      deadline_ms=deadline_ms)

    def compiled_entries(self):
        """Primed compile-cache wrappers across every replica and bucket
        — plus the attached generator's prefill/decode executables —
        (empty unless ``MXNET_COMPILE_CACHE_DIR`` is set or a bundle is
        attached)."""
        out = []
        for rep in self._replicas:
            out.extend(rep.compiled_entries())
        if self._generator is not None:
            out.extend(self._generator.compiled_entries())
        return out

    def save_aot_bundle(self, prefix, epoch):
        """Write this server's compiled executables as an AOT bundle
        beside the checkpoint (``prefix-NNNN.aot/``) with a warmup
        manifest, so the next replica restored from this prefix warms
        deserialize-only.  Requires the compile cache to be enabled (the
        executables must have primed through it)."""
        from ..checkpoint import save_aot_bundle as _save

        entries = self.compiled_entries()
        if not entries:
            raise MXNetError(
                "no cached executables to bundle — set "
                "MXNET_COMPILE_CACHE_DIR before building the server so "
                "its buckets prime through the compile cache")
        warmup = {
            "input_shapes": {k: list(v)
                             for k, v in self._input_shapes.items()},
            "buckets": list(self.buckets),
            "dtype": self._dtype.name,
        }
        if self._generator_spec is not None:
            gen_spec = dict(self._generator_spec)
            draft = gen_spec.get("draft")
            if draft and not isinstance(draft.get("params"), str):
                # the warmup manifest is JSON: in-memory draft weights
                # must travel as a sibling .params file, path-referenced
                from .. import ndarray as nd

                draft = dict(draft)
                dpath = "%s-%04d.draft.params" % (prefix, int(epoch))
                nd.save(dpath, {k: v if isinstance(v, nd.NDArray)
                                else nd.array(np.asarray(v))
                                for k, v in draft["params"].items()})
                draft["params"] = dpath
                gen_spec["draft"] = draft
            warmup["generator"] = gen_spec
        return _save(prefix, epoch, entries, warmup=warmup)

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self._batcher.start()
        self._started = True
        return self

    def warmup(self):
        """Pre-compile every bucket on every replica.  The server is not
        :meth:`ready` until this completes (callers deferring warmup past
        construction get the ``/readyz`` 503-while-warming window)."""
        from .. import faults

        # chaos seam: serving.server.warmup:ioerr=1 fails the warmup after
        # the predictors (and a generator) are device-resident — the
        # partial-allocation path _abort_partial_build must unwind
        faults.fire("serving.server.warmup")
        self._warmed = False
        for rep in self._replicas:
            rep.warmup()
        self._warmed = True
        return self

    def begin_drain(self):
        """Flip to draining WITHOUT stopping: ``ready()`` goes False (so
        ``/readyz`` answers 503 and a router stops dispatching here) while
        in-flight and queued work keeps completing.  The scale-in /
        preemption first step — quiesce arrivals, then :meth:`stop`."""
        self._draining = True
        return self

    def stop(self, drain: bool = True, timeout_ms: Optional[float] = None):
        """Stop the service.  With ``drain`` (default) queued requests are
        flushed before the workers exit — bounded by ``timeout_ms``
        (default ``MXNET_SERVING_DRAIN_TIMEOUT_MS``): past the deadline
        remaining futures are force-cancelled with
        :class:`~mxnet_tpu.serving.batcher.DrainTimeoutError` so a wedged
        worker can never hang retirement.  Without ``drain`` they fail
        fast with :class:`ServerClosedError`.  Idempotent: a second
        ``stop`` (any ``drain`` value) is a no-op rather than re-failing
        futures or re-joining dead workers."""
        if self._stopped:
            return
        self._stopped = True
        self._draining = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._http_thread is not None:
                self._http_thread.join(timeout=5)
                self._http_thread = None
        if timeout_ms is None:
            timeout_ms = env("MXNET_SERVING_DRAIN_TIMEOUT_MS", 30000.0,
                             float)
        if self._generator is not None:
            self._generator.stop(drain=drain, timeout=timeout_ms / 1e3)
        self._batcher.stop(drain=drain, timeout=timeout_ms / 1e3)
        # page-out contract: a stopped server must not pin device memory.
        # Snapshot the compile-behaviour counter while the predictors are
        # still alive, then drop every reference to them (bucket
        # executables, parameter arrays, the generator's KV pool) — the
        # batcher worker threads have exited, so nothing touches them
        # again.  Save any AOT bundle BEFORE stopping: compiled_entries()
        # is empty from here on.
        self._released_cold_runs = self.cold_bucket_runs()
        self._batcher.release()
        self._replicas = []
        self._generator = None
        self._model_params = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)

    # -- request path -----------------------------------------------------
    def _coerce(self, name, value):
        shape = self._item_shapes.get(name)
        if shape is None:
            raise MXNetError("unknown input %r (expected %s)"
                             % (name, sorted(self._item_shapes)))
        arr = np.asarray(value, dtype=self._dtype)
        if arr.shape == (1,) + shape:  # callers may keep a unit batch axis
            arr = arr[0]
        if arr.shape != shape:
            raise MXNetError("input %r has shape %s, expected %s"
                             % (name, arr.shape, shape))
        return arr

    def submit(self, deadline_ms: Optional[float] = None, **inputs) -> Future:
        """Enqueue one request; returns a Future resolving to the per-item
        output list (batch axis stripped).  Raises ``QueueFullError`` when
        admission control rejects, ``ServerClosedError`` after ``stop``;
        the future raises ``DeadlineExceededError`` if ``deadline_ms``
        elapses while the request is still queued."""
        if self._stopped:
            raise ServerClosedError("server is stopped")
        missing = set(self._item_shapes) - set(inputs)
        if missing:
            raise MXNetError("missing inputs %s" % sorted(missing))
        coerced = {k: self._coerce(k, v) for k, v in inputs.items()}
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        future = Future()
        self._batcher.put(coerced, future, deadline)
        return future

    def predict(self, deadline_ms: Optional[float] = None,
                **inputs) -> List[np.ndarray]:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(deadline_ms=deadline_ms, **inputs).result()

    def queue_depth(self):
        return self._batcher.queue_depth()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until the batcher queue is empty and no dequeued batch
        is still executing — the graceful page-out drain barrier (call
        :meth:`begin_drain` first so no new work arrives).  False on
        timeout."""
        if self._batcher is None:
            return True
        return self._batcher.wait_idle(timeout)

    def handoff_streams(self) -> int:
        """Fail every queued and active generate stream with
        :class:`ServerClosedError` so a router-level consumer re-homes
        them on a surviving replica (greedy decode resumes bit-identical
        from prompt + emitted tokens).  Returns the stream count; 0
        without a generator."""
        if self._generator is None:
            return 0
        return self._generator.handoff()

    def health(self):
        """``("ok", [])`` when every replica worker is alive, else
        ``("degraded", [detail, ...])`` listing the dead workers."""
        dead = self._batcher.dead_workers()
        return ("degraded" if dead else "ok", dead)

    def ready(self) -> bool:
        """Readiness (distinct from liveness): True only when the server
        is started, warmed (or warmup was explicitly opted out), not
        draining/stopped, and at least one replica worker survives.  A
        router must never dispatch to a warming or draining replica —
        that is this predicate, surfaced over HTTP as ``/readyz``."""
        if not self._started or self._draining or self._stopped \
                or not self._warmed:
            return False
        return len(self._batcher.dead_workers()) < len(self._replicas)

    def ready_state(self) -> str:
        """Why-not-ready detail for ``/readyz``: one of ``ready`` /
        ``starting`` / ``warming`` / ``draining`` / ``stopped`` /
        ``dead``."""
        if self._stopped:
            return "stopped"
        if self._draining:
            return "draining"
        if not self._warmed:
            return "warming"
        if not self._started:
            return "starting"
        if len(self._batcher.dead_workers()) >= len(self._replicas):
            return "dead"
        return "ready"

    def swap(self, prefix, epoch):
        """In-place zero-downtime checkpoint hot-swap.

        Builds a fresh shadow :class:`BucketedPredictor` family per
        context from ``prefix-symbol.json`` / ``prefix-NNNN.params``,
        warms **every** bucket on it (so post-swap steady state never
        recompiles), then atomically flips the batcher onto the new
        predictors.  The batch in flight finishes on the old weights;
        the very next flush runs the new ones.  The server keeps
        accepting and serving requests throughout — readiness never
        drops.  Serialized: concurrent ``swap`` calls queue up.

        With the compile cache enabled the shadow predictors inherit the
        outgoing replica's executables (same graph + shapes -> same
        content fingerprint, served from the in-process cache), so the
        shadow warmup performs zero fresh XLA compiles — swap latency is
        parameter-loading, not compilation."""
        from .. import faults

        faults.fire("serving.server.swap")
        symbol = "%s-symbol.json" % prefix
        params = "%s-%04d.params" % (prefix, epoch)
        with self._swap_lock:
            shadows = [
                BucketedPredictor(symbol, params, self._item_shapes,
                                  self.buckets, ctx=c, dtype=self._dtype)
                for c in self._ctxs]
            for rep in shadows:
                rep.warmup()
            shadow_gen = None
            if self._generator is not None:
                from ..generation import DecodeEngine

                # warm a shadow engine on the new params before the flip;
                # in-flight streams finish on the old engine as it drains
                shadow_gen = DecodeEngine(
                    params, ctx=self._ctxs[0], dtype=self._dtype,
                    warmup=True, start=True, **self._generator_spec)
            self._batcher.swap_replicas(shadows)
            self._replicas = shadows
            if shadow_gen is not None:
                old_gen, self._generator = self._generator, shadow_gen
                threading.Thread(
                    target=old_gen.stop, kwargs={"drain": True},
                    name="mxtpu-gen-swap-drain", daemon=True).start()
        from .. import telemetry as _tm

        _tm.log_event("serving_swap", prefix=prefix, epoch=int(epoch),
                      buckets=list(self.buckets))
        return self

    def swap_config(self) -> Dict:
        """Constructor kwargs (minus the model) a router needs to build a
        shadow server of this one — same shapes, buckets, batching knobs,
        contexts, and dtype."""
        cfg = {
            "input_shapes": dict(self._input_shapes),
            "buckets": tuple(self.buckets),
            "max_wait_us": self._max_wait_us,
            "max_queue": self._max_queue,
            "ctx": list(self._ctxs),
            "dtype": self._dtype,
        }
        if self._generator_spec is not None:
            cfg["generator_spec"] = dict(self._generator_spec)
        return cfg

    def cold_bucket_runs(self) -> int:
        """Post-warmup flushes that hit a never-warmed bucket, summed
        over replicas — the observable recompile counter for the
        "steady state never recompiles" acceptance check.  The count
        survives :meth:`stop` (which releases the predictors): the
        platform's paging acceptance reads it on paged-out servers."""
        n = self._released_cold_runs \
            + sum(rep.cold_runs for rep in self._replicas)
        if self._generator is not None:
            n += self._generator.cold_decode_runs()
        return n

    def resident_bytes(self) -> int:
        """Estimated bytes of device-resident model state this server
        pins: every replica's bound parameter/aux arrays (buckets share
        one copy per context through ``Predictor.reshape``).  0 once
        :meth:`stop` has released the predictors — the observable the
        platform's ``mxtpu_platform_resident_bytes`` gauge sums, proving
        a page-out actually returned the memory."""
        from ..sharding.placement import param_bytes

        arrays = []
        for rep in self._replicas:
            base = rep._preds[rep.buckets[-1]]
            arrays.extend(base._exec.arg_dict.values())
            arrays.extend(base._exec.aux_dict.values())
        if not arrays:
            return 0
        return param_bytes(arrays)[1]

    def metrics_text(self):
        return self.metrics.render_text()

    # -- HTTP front end ---------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the stdlib HTTP endpoint in a daemon thread; returns the
        bound ``(host, port)``.

        * ``POST /predict`` — body ``{"inputs": {name: nested list},
          "deadline_ms": optional}`` → ``{"outputs": [...]}``; 503 when
          the queue is full (retry with backoff), 504 past deadline.  An
          ``X-Deadline-Ms`` request header sets the deadline too (the
          body field wins when both are present).
        * ``POST /generate`` — body ``{"prompt": [token ids],
          "max_new_tokens": optional, "deadline_ms": optional}`` →
          newline-delimited JSON token stream (``application/x-ndjson``),
          one ``{"token": t}`` line flushed per decoded token and a final
          ``{"done": true, ...}`` line; the connection closes to delimit
          the stream.  429 when generation admission rejects (retry with
          backoff), 404 when no generator is attached.
        * ``POST /swap`` — body ``{"prefix": ..., "epoch": N}``: in-place
          warm checkpoint hot-swap (every bucket pre-compiled on the new
          params before the atomic flip; serving never pauses).
        * ``GET /metrics`` — Prometheus text.
        * ``GET /healthz`` — liveness: 200 ``ok`` when every replica
          worker thread is alive; 503 with a JSON
          ``{"status": "degraded", "dead_workers": [...]}`` body when one
          has died (the server limps on through surviving replicas, but
          the orchestrator should recycle it).
        * ``GET /readyz`` — readiness: 200 ``ready`` only when the server
          should receive traffic; 503 ``{"status": "warming" | "draining"
          | ...}`` while warming up, draining, or stopped, so a router
          never routes to a warming/draining replica.  Liveness semantics
          on ``/healthz`` are unchanged.
        """
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # keep pytest/console output clean
                pass

            def _reply(self, code, body, ctype="application/json"):
                data = body if isinstance(body, bytes) else body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/metrics":
                    self._reply(200, server.metrics_text(),
                                ctype="text/plain; version=0.0.4")
                elif self.path == "/healthz":
                    status, dead = server.health()
                    if status == "ok":
                        self._reply(200, "ok", ctype="text/plain")
                    else:
                        self._reply(503, json.dumps(
                            {"status": "degraded", "dead_workers": dead}))
                elif self.path == "/readyz":
                    if server.ready():
                        self._reply(200, "ready", ctype="text/plain")
                    else:
                        self._reply(503, json.dumps(
                            {"status": server.ready_state()}))
                else:
                    self._reply(404, json.dumps({"error": "not found"}))

            def _generate(self, req):
                """Stream tokens as NDJSON lines, flushed one per decode
                step; HTTP/1.0-style connection close delimits the
                stream (no Content-Length)."""
                deadline_ms = req.get("deadline_ms")
                if deadline_ms is None:
                    hdr = self.headers.get("X-Deadline-Ms")
                    if hdr:
                        deadline_ms = float(hdr)
                try:
                    stream = server.submit_generate(
                        req.get("prompt", []),
                        req.get("max_new_tokens"),
                        deadline_ms=deadline_ms)
                except QueueFullError as exc:
                    self._reply(429, json.dumps({"error": str(exc)}))
                    return
                except ServerClosedError as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                    return
                except (MXNetError, ValueError, TypeError) as exc:
                    code = 404 if "no generator attached" in str(exc) \
                        else 400
                    self._reply(code, json.dumps({"error": repr(exc)}))
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Accel-Buffering", "no")
                self.end_headers()
                self.close_connection = True
                try:
                    for tok in stream:
                        self.wfile.write(
                            (json.dumps({"token": int(tok)}) + "\n")
                            .encode())
                        self.wfile.flush()
                    self.wfile.write((json.dumps(
                        {"done": True, "n": len(stream.tokens),
                         "ttft_ms": stream.ttft_ms}) + "\n").encode())
                    self.wfile.flush()
                except BrokenPipeError:
                    pass  # client went away mid-stream
                except BaseException as exc:
                    # 200 already sent: signal failure in-band so the
                    # router can resume the stream on another replica
                    try:
                        self.wfile.write((json.dumps(
                            {"error": repr(exc)}) + "\n").encode())
                        self.wfile.flush()
                    except OSError:
                        pass

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/generate":
                        self._generate(req)
                        return
                    if self.path == "/swap":
                        server.swap(req["prefix"], int(req["epoch"]))
                        self._reply(200, json.dumps(
                            {"swapped": True, "epoch": int(req["epoch"])}))
                        return
                    if self.path != "/predict":
                        self._reply(404, json.dumps({"error": "not found"}))
                        return
                    deadline_ms = req.get("deadline_ms")
                    if deadline_ms is None:
                        hdr = self.headers.get("X-Deadline-Ms")
                        if hdr:
                            deadline_ms = float(hdr)
                    fut = server.submit(deadline_ms=deadline_ms,
                                        **req.get("inputs", {}))
                    outs = fut.result()
                    self._reply(200, json.dumps(
                        {"outputs": [np.asarray(o).tolist() for o in outs]}))
                except QueueFullError as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                except DeadlineExceededError as exc:
                    self._reply(504, json.dumps({"error": str(exc)}))
                except ServerClosedError as exc:
                    self._reply(503, json.dumps({"error": str(exc)}))
                except (MXNetError, ValueError, TypeError, KeyError,
                        OSError, json.JSONDecodeError) as exc:
                    self._reply(400, json.dumps({"error": repr(exc)}))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-serving-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address


def install_preemption_handler(server, deregister=None, sig=None,
                               drain_timeout_ms=None, exit_process=True):
    """Install the serving preemption path on ``sig`` (default SIGTERM),
    mirroring the training workers' handler (kvstore.py): flip the
    replica to draining (``/readyz`` 503 so routers stop dispatching),
    run ``deregister`` if given (drop out of the replica registry so
    replicated routers converge before the process dies), drain bounded
    by ``MXNET_SERVING_DRAIN_TIMEOUT_MS``, dump a flight-recorder
    postmortem, and exit 0 — autoscaler retirement and cluster
    preemption share this one path, and a clean preemption must not
    look like a crash to the launcher.  Returns the handler (tests
    invoke it directly); the signal itself is only hooked from the main
    thread (``signal.signal`` constraint — elsewhere the handler comes
    back uninstalled)."""
    import logging
    import os
    import signal as _signal

    if sig is None:
        sig = _signal.SIGTERM
    fired = threading.Event()

    def handler(signum=None, frame=None):
        if fired.is_set():
            return
        fired.set()
        logging.info("serving preemption signal: draining, deregistering")
        try:
            server.begin_drain()
        except Exception as e:
            logging.warning("preemption begin_drain failed: %s", e)
        if deregister is not None:
            try:
                deregister()
            except Exception as e:
                logging.warning("preemption deregister failed: %s", e)
        try:
            server.stop(drain=True, timeout_ms=drain_timeout_ms)
        except Exception as e:
            logging.warning("preemption drain/stop failed: %s", e)
        try:
            # flight recorder: the postmortem is the only record of this
            # replica's final state once we _exit (no atexit hooks run)
            from .. import telemetry as _tm

            _tm.flight_recorder.dump("preemption-sigterm-serving")
        except Exception:
            pass
        if exit_process:
            os._exit(0)

    if threading.current_thread() is threading.main_thread():
        try:
            _signal.signal(sig, handler)
        except (ValueError, OSError):
            pass
    return handler

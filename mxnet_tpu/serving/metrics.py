"""Serving metrics — QPS, latency quantiles, queue depth, batch histograms.

The reference framework had no serving tier at all; this follows the
conventions production model servers converged on (TF Serving / Triton):
a small set of counters + histograms, exported in Prometheus text format,
cheap enough to update on every request.  Batches are additionally emitted
as :class:`mxnet_tpu.profiler.Frame` spans, so a
``profiler_set_state("run")`` / ``dump_profile()`` around serving traffic
shows each flushed batch on the chrome-trace timeline next to the
executor's own events.

Storage lives on the shared :mod:`mxnet_tpu.telemetry` registry (one
private :class:`~mxnet_tpu.telemetry.Registry` per server, registered as a
collector so the series also appear in ``telemetry.render_prometheus()``);
:meth:`render_text` keeps the original byte-exact Prometheus exposition —
every pre-existing ``mxtpu_serving_*`` line renders unchanged.  The
latency quantile reservoir and QPS sliding window are summary-type
estimates with no registry analogue and stay local.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .. import telemetry as _telemetry

__all__ = ["ServingMetrics"]

# sliding window for QPS, seconds
_QPS_WINDOW = 60.0
# bounded reservoir of per-request latencies for the quantile estimates
_LATENCY_SAMPLES = 4096

_COUNTER_KEYS = ("requests_total", "requests_completed", "requests_rejected",
                 "requests_expired", "requests_failed", "worker_crashes",
                 "batches_total", "padded_items_total")


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class ServingMetrics:
    """Thread-safe counters for one :class:`InferenceServer`.

    ``batch_size_hist`` is keyed by the *bucket* (padded shape) each flush
    ran at — its key set is exactly the set of distinct compiled shapes the
    server exercised, and the sum of its counts is the number of underlying
    executor invocations.  ``occupancy_hist`` is keyed by the number of
    real (un-padded) requests in each flush.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        reg = self._registry = _telemetry.Registry()
        self._c = {k: reg.counter("mxtpu_serving_%s" % k)
                   for k in _COUNTER_KEYS}
        self._g_depth = reg.gauge("mxtpu_serving_queue_depth")
        self._g_peak = reg.gauge("mxtpu_serving_queue_depth_peak")
        self._batch_hist = reg.labeled_counter("mxtpu_serving_batch_size",
                                               "bucket")
        self._occ_hist = reg.labeled_counter("mxtpu_serving_batch_occupancy",
                                             "n")
        self._latencies = deque(maxlen=_LATENCY_SAMPLES)
        self._completions = deque()  # monotonic stamps inside _QPS_WINDOW
        _telemetry.register_collector(self)

    # -- update hooks (called by the batcher/server) ----------------------
    def on_submit(self, queue_depth):
        self._c["requests_total"].inc()
        self._g_depth.set(queue_depth)
        self._g_peak.set_max(queue_depth)

    def on_reject(self):
        self._c["requests_rejected"].inc()

    def on_expire(self, n=1):
        self._c["requests_expired"].inc(n)

    def on_fail(self, n=1):
        self._c["requests_failed"].inc(n)

    def on_worker_crash(self):
        self._c["worker_crashes"].inc()

    def on_dequeue(self, queue_depth):
        self._g_depth.set(queue_depth)

    def on_batch(self, bucket, occupancy):
        self._c["batches_total"].inc()
        self._c["padded_items_total"].inc(bucket - occupancy)
        self._batch_hist.inc(int(bucket))
        self._occ_hist.inc(int(occupancy))

    def on_complete(self, latency_ms):
        now = time.monotonic()
        self._c["requests_completed"].inc()
        with self._lock:
            self._latencies.append(latency_ms)
            self._completions.append(now)
            cutoff = now - _QPS_WINDOW
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()

    # -- export -----------------------------------------------------------
    def qps(self):
        now = time.monotonic()
        with self._lock:
            cutoff = now - _QPS_WINDOW
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()
            span = min(max(now - self._t0, 1e-9), _QPS_WINDOW)
            return len(self._completions) / span

    def snapshot(self):
        """One consistent dict of everything (the JSON-side export)."""
        qps = self.qps()
        with self._lock:
            lat = sorted(self._latencies)
        out = {k: self._c[k].value for k in _COUNTER_KEYS}
        out.update({
            "queue_depth": self._g_depth.value,
            "queue_depth_peak": self._g_peak.value,
            "batch_size_hist": self._batch_hist.snapshot(),
            "occupancy_hist": self._occ_hist.snapshot(),
            "latency_ms_p50": _percentile(lat, 0.50),
            "latency_ms_p99": _percentile(lat, 0.99),
            "qps": qps,
        })
        return out

    def render_text(self):
        """Prometheus text exposition of :meth:`snapshot` — byte-compatible
        with the pre-registry renderer for every metric name."""
        s = self.snapshot()
        lines = []
        for key in _COUNTER_KEYS:
            lines.append("# TYPE mxtpu_serving_%s counter" % key)
            lines.append("mxtpu_serving_%s %d" % (key, s[key]))
        lines.append("# TYPE mxtpu_serving_queue_depth gauge")
        lines.append("mxtpu_serving_queue_depth %d" % s["queue_depth"])
        lines.append("mxtpu_serving_queue_depth_peak %d"
                     % s["queue_depth_peak"])
        lines.append("# TYPE mxtpu_serving_batch_size histogram")
        for b in sorted(s["batch_size_hist"]):
            lines.append('mxtpu_serving_batch_size{bucket="%d"} %d'
                         % (b, s["batch_size_hist"][b]))
        for n in sorted(s["occupancy_hist"]):
            lines.append('mxtpu_serving_batch_occupancy{n="%d"} %d'
                         % (n, s["occupancy_hist"][n]))
        lines.append("# TYPE mxtpu_serving_latency_ms summary")
        lines.append('mxtpu_serving_latency_ms{quantile="0.5"} %.3f'
                     % s["latency_ms_p50"])
        lines.append('mxtpu_serving_latency_ms{quantile="0.99"} %.3f'
                     % s["latency_ms_p99"])
        lines.append("# TYPE mxtpu_serving_qps gauge")
        lines.append("mxtpu_serving_qps %.3f" % s["qps"])
        return "\n".join(lines) + "\n"

    def render_prometheus(self):
        """Collector hook for ``telemetry.render_prometheus()``."""
        return self.render_text()

"""Serving metrics — QPS, latency quantiles, queue depth, batch histograms.

The reference framework had no serving tier at all; this follows the
conventions production model servers converged on (TF Serving / Triton):
a small set of counters + histograms, exported in Prometheus text format,
cheap enough to update on every request under a single lock.  Batches are
additionally emitted as :class:`mxnet_tpu.profiler.Frame` spans, so a
``profiler_set_state("run")`` / ``dump_profile()`` around serving traffic
shows each flushed batch on the chrome-trace timeline next to the
executor's own events.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict

__all__ = ["ServingMetrics"]

# sliding window for QPS, seconds
_QPS_WINDOW = 60.0
# bounded reservoir of per-request latencies for the quantile estimates
_LATENCY_SAMPLES = 4096


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class ServingMetrics:
    """Thread-safe counters for one :class:`InferenceServer`.

    ``batch_size_hist`` is keyed by the *bucket* (padded shape) each flush
    ran at — its key set is exactly the set of distinct compiled shapes the
    server exercised, and the sum of its counts is the number of underlying
    executor invocations.  ``occupancy_hist`` is keyed by the number of
    real (un-padded) requests in each flush.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.requests_rejected = 0
        self.requests_expired = 0
        self.requests_failed = 0
        self.requests_completed = 0
        self.worker_crashes = 0
        self.batches_total = 0
        self.padded_items_total = 0
        self.queue_depth = 0
        self.queue_depth_peak = 0
        self.batch_size_hist: Dict[int, int] = {}
        self.occupancy_hist: Dict[int, int] = {}
        self._latencies = deque(maxlen=_LATENCY_SAMPLES)
        self._completions = deque()  # monotonic stamps inside _QPS_WINDOW

    # -- update hooks (called by the batcher/server) ----------------------
    def on_submit(self, queue_depth):
        with self._lock:
            self.requests_total += 1
            self.queue_depth = queue_depth
            self.queue_depth_peak = max(self.queue_depth_peak, queue_depth)

    def on_reject(self):
        with self._lock:
            self.requests_rejected += 1

    def on_expire(self, n=1):
        with self._lock:
            self.requests_expired += n

    def on_fail(self, n=1):
        with self._lock:
            self.requests_failed += n

    def on_worker_crash(self):
        with self._lock:
            self.worker_crashes += 1

    def on_dequeue(self, queue_depth):
        with self._lock:
            self.queue_depth = queue_depth

    def on_batch(self, bucket, occupancy):
        with self._lock:
            self.batches_total += 1
            self.padded_items_total += bucket - occupancy
            self.batch_size_hist[bucket] = \
                self.batch_size_hist.get(bucket, 0) + 1
            self.occupancy_hist[occupancy] = \
                self.occupancy_hist.get(occupancy, 0) + 1

    def on_complete(self, latency_ms):
        now = time.monotonic()
        with self._lock:
            self.requests_completed += 1
            self._latencies.append(latency_ms)
            self._completions.append(now)
            cutoff = now - _QPS_WINDOW
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()

    # -- export -----------------------------------------------------------
    def qps(self):
        now = time.monotonic()
        with self._lock:
            cutoff = now - _QPS_WINDOW
            while self._completions and self._completions[0] < cutoff:
                self._completions.popleft()
            span = min(max(now - self._t0, 1e-9), _QPS_WINDOW)
            return len(self._completions) / span

    def snapshot(self):
        """One consistent dict of everything (the JSON-side export)."""
        qps = self.qps()
        with self._lock:
            lat = sorted(self._latencies)
            return {
                "requests_total": self.requests_total,
                "requests_completed": self.requests_completed,
                "requests_rejected": self.requests_rejected,
                "requests_expired": self.requests_expired,
                "requests_failed": self.requests_failed,
                "worker_crashes": self.worker_crashes,
                "batches_total": self.batches_total,
                "padded_items_total": self.padded_items_total,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
                "batch_size_hist": dict(self.batch_size_hist),
                "occupancy_hist": dict(self.occupancy_hist),
                "latency_ms_p50": _percentile(lat, 0.50),
                "latency_ms_p99": _percentile(lat, 0.99),
                "qps": qps,
            }

    def render_text(self):
        """Prometheus text exposition of :meth:`snapshot`."""
        s = self.snapshot()
        lines = []
        for key in ("requests_total", "requests_completed",
                    "requests_rejected", "requests_expired",
                    "requests_failed", "worker_crashes", "batches_total",
                    "padded_items_total"):
            lines.append("# TYPE mxtpu_serving_%s counter" % key)
            lines.append("mxtpu_serving_%s %d" % (key, s[key]))
        lines.append("# TYPE mxtpu_serving_queue_depth gauge")
        lines.append("mxtpu_serving_queue_depth %d" % s["queue_depth"])
        lines.append("mxtpu_serving_queue_depth_peak %d"
                     % s["queue_depth_peak"])
        lines.append("# TYPE mxtpu_serving_batch_size histogram")
        for b in sorted(s["batch_size_hist"]):
            lines.append('mxtpu_serving_batch_size{bucket="%d"} %d'
                         % (b, s["batch_size_hist"][b]))
        for n in sorted(s["occupancy_hist"]):
            lines.append('mxtpu_serving_batch_occupancy{n="%d"} %d'
                         % (n, s["occupancy_hist"][n]))
        lines.append("# TYPE mxtpu_serving_latency_ms summary")
        lines.append('mxtpu_serving_latency_ms{quantile="0.5"} %.3f'
                     % s["latency_ms_p50"])
        lines.append('mxtpu_serving_latency_ms{quantile="0.99"} %.3f'
                     % s["latency_ms_p99"])
        lines.append("# TYPE mxtpu_serving_qps gauge")
        lines.append("mxtpu_serving_qps %.3f" % s["qps"])
        return "\n".join(lines) + "\n"

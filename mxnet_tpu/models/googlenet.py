"""GoogLeNet / Inception-v1 symbol builder (Szegedy et al. 2014).

Capability parity with reference example/image-classification/symbols/
googlenet.py — written fresh; inception branches concatenate on the channel
axis, auxiliary classifiers omitted (as in the reference's training config).
"""
from .. import symbol as sym


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name="%s_conv" % name)
    return sym.Activation(c, act_type="relu", name="%s_relu" % name)


def _inception(data, f1, f3r, f3, f5r, f5, proj, name):
    b1 = _conv(data, f1, (1, 1), name="%s_1x1" % name)
    b3 = _conv(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = _conv(b3, f3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    b5 = _conv(data, f5r, (1, 1), name="%s_5x5r" % name)
    b5 = _conv(b5, f5, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    bp = sym.Pooling(data, pool_type="max", kernel=(3, 3), stride=(1, 1),
                     pad=(1, 1), name="%s_pool" % name)
    bp = _conv(bp, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b3, b5, bp, dim=1, name="%s_out" % name)


def get_googlenet(num_classes=1000):
    net = sym.Variable("data")
    net = _conv(net, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="stem1")
    net = sym.Pooling(net, pool_type="max", kernel=(3, 3), stride=(2, 2),
                      pad=(1, 1), name="pool1")
    net = _conv(net, 64, (1, 1), name="stem2r")
    net = _conv(net, 192, (3, 3), pad=(1, 1), name="stem2")
    net = sym.Pooling(net, pool_type="max", kernel=(3, 3), stride=(2, 2),
                      pad=(1, 1), name="pool2")
    net = _inception(net, 64, 96, 128, 16, 32, 32, "in3a")
    net = _inception(net, 128, 128, 192, 32, 96, 64, "in3b")
    net = sym.Pooling(net, pool_type="max", kernel=(3, 3), stride=(2, 2),
                      pad=(1, 1), name="pool3")
    net = _inception(net, 192, 96, 208, 16, 48, 64, "in4a")
    net = _inception(net, 160, 112, 224, 24, 64, 64, "in4b")
    net = _inception(net, 128, 128, 256, 24, 64, 64, "in4c")
    net = _inception(net, 112, 144, 288, 32, 64, 64, "in4d")
    net = _inception(net, 256, 160, 320, 32, 128, 128, "in4e")
    net = sym.Pooling(net, pool_type="max", kernel=(3, 3), stride=(2, 2),
                      pad=(1, 1), name="pool4")
    net = _inception(net, 256, 160, 320, 32, 128, 128, "in5a")
    net = _inception(net, 384, 192, 384, 48, 128, 128, "in5b")
    net = sym.Pooling(net, global_pool=True, pool_type="avg", kernel=(7, 7),
                      name="global_pool")
    net = sym.Dropout(net, p=0.4, name="drop")
    net = sym.FullyConnected(sym.Flatten(net), num_hidden=num_classes,
                             name="fc")
    return sym.SoftmaxOutput(net, name="softmax")

"""Decoder-only transformer LM symbol builder — the TPU-native flagship
model family (beyond the 2017 reference, which predates transformers; its
sequence-model slot was the RNN stack, rnn/rnn_cell.py).

Rides the framework's high-MFU path: attention through the Pallas
flash-attention kernels (``_contrib_FlashAttention``, fwd+bwd, K/V
streamed — ops/attention.py), all matmuls MXU-shaped, pre-norm residual
blocks with LayerNorm/gelu. Sequence parallelism for longer-than-HBM
contexts lives in ``parallel.ring`` / ``parallel.mesh``.
"""

from .. import symbol as sym


def _dense(x, n_in, n_out, name):
    """FC over the trailing dim of a (b, s, d) tensor (FullyConnected is
    2-D, reference fully_connected-inl.h): reshape to rows and back."""
    h = sym.Reshape(x, shape=(-1, n_in))
    h = sym.FullyConnected(h, num_hidden=n_out, name=name)
    return h


def _block(x, hidden, num_heads, seq_len, name, block_q=None, block_k=None,
           attn_impl="flash"):
    head_dim = hidden // num_heads
    # attention sublayer (pre-norm)
    h = sym.LayerNorm(x, name="%s_ln1" % name)
    qkv = _dense(h, hidden, 3 * hidden, "%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, head_dim))
    q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=2, squeeze_axis=True,
                               name="%s_split" % name)
    if attn_impl == "splash":
        # upstream splash kernel (ops/attention.py splash_attention) —
        # the A/B alternative to the in-tree flash kernels
        att = sym._contrib_SplashAttention(q, k, v, causal=True,
                                           name="%s_attn" % name)
    elif attn_impl == "flash":
        att = sym._contrib_FlashAttention(q, k, v, causal=True,
                                          block_q=block_q, block_k=block_k,
                                          name="%s_attn" % name)
    else:
        raise ValueError("attn_impl must be 'flash' or 'splash', got %r"
                         % (attn_impl,))
    att = sym.Reshape(att, shape=(-1, seq_len, hidden))
    proj = _dense(att, hidden, hidden, "%s_proj" % name)
    x = sym.broadcast_add(x, sym.Reshape(proj, shape=(-1, seq_len, hidden)),
                          name="%s_res1" % name)
    # mlp sublayer (pre-norm, gelu)
    h = sym.LayerNorm(x, name="%s_ln2" % name)
    h = _dense(h, hidden, 4 * hidden, "%s_fc1" % name)
    h = sym.gelu(h, name="%s_gelu" % name)
    h = _dense(h, 4 * hidden, hidden, "%s_fc2" % name)
    return sym.broadcast_add(x, sym.Reshape(h, shape=(-1, seq_len, hidden)),
                             name="%s_res2" % name)


def get_transformer_lm(vocab_size=32000, num_layers=4, num_heads=8,
                       hidden=512, seq_len=128, block_q=None, block_k=None,
                       attn_impl="flash"):
    """Causal LM: data (b, seq_len) token ids -> SoftmaxOutput over the
    vocab at every position (label (b*seq_len,) next-token ids).
    ``attn_impl``: "flash" (in-tree Pallas kernels) or "splash"
    (upstream jax splash attention)."""
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(1, seq_len, hidden))
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    x = sym.broadcast_add(x, pos, name="pos_add")
    for i in range(num_layers):
        x = _block(x, hidden, num_heads, seq_len, "layer%d" % i,
                   block_q=block_q, block_k=block_k, attn_impl=attn_impl)
    x = sym.LayerNorm(x, name="ln_f")
    logits = _dense(x, hidden, vocab_size, "lm_head")  # (b*s, vocab)
    # label arrives (b, seq_len) from the iterator; flatten inside the
    # symbol like the reference LM examples (example/rnn/lstm_bucketing.py)
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(logits, label=label, name="softmax")


# ---------------------------------------------------------------------------
# Generative-serving variants (mxnet_tpu.generation) — same weight names as
# get_transformer_lm, so one trained checkpoint binds all three symbols.
# ---------------------------------------------------------------------------


def _prefill_block(x, hidden, num_heads, seq_len, name, attn_impl):
    """Pre-norm block that also RETURNS its (k, v) projections — the
    prefill pass feeds them into the paged KV pool so decode never
    recomputes the prefix."""
    head_dim = hidden // num_heads
    h = sym.LayerNorm(x, name="%s_ln1" % name)
    qkv = _dense(h, hidden, 3 * hidden, "%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, head_dim))
    q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=2, squeeze_axis=True,
                               name="%s_split" % name)
    if attn_impl == "dense":
        # dense oracle attention: prefill runs once per sequence and must
        # be CPU-fast (interpret-mode Pallas is not), TPU still fuses it
        att = sym._contrib_DenseAttention(q, k, v, causal=True,
                                          name="%s_attn" % name)
    elif attn_impl == "flash":
        att = sym._contrib_FlashAttention(q, k, v, causal=True,
                                          name="%s_attn" % name)
    else:
        raise ValueError("attn_impl must be 'dense' or 'flash', got %r"
                         % (attn_impl,))
    att = sym.Reshape(att, shape=(-1, seq_len, hidden))
    proj = _dense(att, hidden, hidden, "%s_proj" % name)
    x = sym.broadcast_add(x, sym.Reshape(proj, shape=(-1, seq_len, hidden)),
                          name="%s_res1" % name)
    h = sym.LayerNorm(x, name="%s_ln2" % name)
    h = _dense(h, hidden, 4 * hidden, "%s_fc1" % name)
    h = sym.gelu(h, name="%s_gelu" % name)
    h = _dense(h, 4 * hidden, hidden, "%s_fc2" % name)
    x = sym.broadcast_add(x, sym.Reshape(h, shape=(-1, seq_len, hidden)),
                          name="%s_res2" % name)
    return x, k, v


def get_transformer_lm_prefill(vocab_size=32000, num_layers=4, num_heads=8,
                               hidden=512, seq_len=128, max_seq_len=None,
                               attn_impl="dense"):
    """Prefill pass for generation: ``data`` (b, seq_len) token ids ->
    ``Group([logits, k0, v0, k1, v1, ...])`` with logits (b, seq_len,
    vocab) and per-layer K/V (b, seq_len, heads, head_dim).

    ``seq_len`` is this executable's (bucketed) prompt capacity;
    ``max_seq_len`` (default ``seq_len``) is the position-table capacity
    shared with the training symbol — the engine builds one prefill
    executor per length bucket against one ``pos_embed_weight``.
    Prompts shorter than ``seq_len`` are right-padded by the caller;
    causal attention keeps the padding from contaminating real
    positions, so only outputs at < length are meaningful."""
    if max_seq_len is None:
        max_seq_len = seq_len
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    if seq_len != max_seq_len:
        pos = sym.slice_axis(pos, axis=1, begin=0, end=seq_len,
                             name="pos_slice")
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    x = sym.broadcast_add(x, pos, name="pos_add")
    kvs = []
    for i in range(num_layers):
        x, k, v = _prefill_block(x, hidden, num_heads, seq_len,
                                 "layer%d" % i, attn_impl)
        kvs.extend([k, v])
    x = sym.LayerNorm(x, name="ln_f")
    logits = _dense(x, hidden, vocab_size, "lm_head")
    logits = sym.Reshape(logits, shape=(-1, seq_len, vocab_size),
                         name="logits")
    return sym.Group([logits] + kvs)


def get_transformer_lm_verify(vocab_size=32000, num_layers=4, num_heads=8,
                              hidden=512, max_seq_len=128, lanes=8,
                              num_pages=64, page_size=16, max_pages=8,
                              width=4):
    """Speculative-decoding verification: ``width`` sequential decode
    steps over paged KV fused into ONE executable, so the target model
    scores a drafted token run in a single dispatch.

    Inputs: ``data`` (lanes, width) token ids — position ``w`` of a lane
    is the token fed at step ``w`` (the last accepted token followed by
    draft proposals); ``positions`` (lanes, width) their absolute
    positions; ``page_table`` (lanes, max_pages); per-layer
    ``layer%d_k_pool`` / ``layer%d_v_pool``.  Output:
    ``Group([logits_0 .. logits_{width-1}, k_pool0_out, v_pool0_out,
    ...])`` with each logits (lanes, vocab).

    Bit-identity by construction: the graph is literally ``width``
    copies of :func:`get_transformer_lm_decode`'s per-token block —
    same ops, same shapes, same paged-attention numerics — chained
    through the pool outputs, so greedy argmax over ``logits_w`` equals
    what ``width`` separate decode steps would produce.  Weights are
    shared across the copies via explicit parameter variables carrying
    the training checkpoint's names."""
    head_dim = hidden // num_heads
    data = sym.Variable("data")
    positions = sym.Variable("positions")
    page_table = sym.Variable("page_table")
    pos_tab = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    pe_flat = sym.Reshape(pos_tab, shape=(max_seq_len, hidden),
                          name="pos_flat")
    embed_w = sym.Variable("tok_embed_weight")

    def _params(name, outs):
        return {"weight": sym.Variable("%s_weight" % name),
                "bias": sym.Variable("%s_bias" % name),
                "num_hidden": outs}

    def _norm(name):
        return {"gamma": sym.Variable("%s_gamma" % name),
                "beta": sym.Variable("%s_beta" % name)}

    k_pools = [sym.Variable("layer%d_k_pool" % i) for i in range(num_layers)]
    v_pools = [sym.Variable("layer%d_v_pool" % i) for i in range(num_layers)]
    logits_outs = []
    for w in range(width):
        tag = "_s%d" % w
        tok = sym.Reshape(sym.slice_axis(data, axis=1, begin=w, end=w + 1,
                                         name="tok_slice%s" % tag),
                          shape=(-1,), name="tok%s" % tag)
        pos_w = sym.Reshape(sym.slice_axis(positions, axis=1, begin=w,
                                           end=w + 1,
                                           name="pos_slice%s" % tag),
                            shape=(-1,), name="pos%s" % tag)
        x = sym.Embedding(tok, weight=embed_w, input_dim=vocab_size,
                          output_dim=hidden, name="tok_embed%s" % tag)
        pe = sym.take(pe_flat, pos_w, name="pos_take%s" % tag)
        x = sym.broadcast_add(x, pe, name="pos_add%s" % tag)
        for i in range(num_layers):
            name = "layer%d" % i
            h = sym.LayerNorm(x, name="%s_ln1%s" % (name, tag),
                              **_norm("%s_ln1" % name))
            qkv = sym.FullyConnected(h, name="%s_qkv%s" % (name, tag),
                                     **_params("%s_qkv" % name, 3 * hidden))
            qkv = sym.Reshape(qkv, shape=(-1, 3, num_heads, head_dim),
                              name="%s_qkvr%s" % (name, tag))
            q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=1,
                                       squeeze_axis=True,
                                       name="%s_split%s" % (name, tag))
            att, k_out, v_out = sym._contrib_PagedAttention(
                q, k, v, k_pools[i], v_pools[i], page_table, pos_w,
                page_size=page_size, name="%s_attn%s" % (name, tag))
            k_pools[i], v_pools[i] = k_out, v_out
            att = sym.Reshape(att, shape=(-1, hidden),
                              name="%s_attr%s" % (name, tag))
            proj = sym.FullyConnected(att, name="%s_proj%s" % (name, tag),
                                      **_params("%s_proj" % name, hidden))
            x = sym.broadcast_add(x, proj, name="%s_res1%s" % (name, tag))
            h = sym.LayerNorm(x, name="%s_ln2%s" % (name, tag),
                              **_norm("%s_ln2" % name))
            h = sym.FullyConnected(h, name="%s_fc1%s" % (name, tag),
                                   **_params("%s_fc1" % name, 4 * hidden))
            h = sym.gelu(h, name="%s_gelu%s" % (name, tag))
            h = sym.FullyConnected(h, name="%s_fc2%s" % (name, tag),
                                   **_params("%s_fc2" % name, hidden))
            x = sym.broadcast_add(x, h, name="%s_res2%s" % (name, tag))
        x = sym.LayerNorm(x, name="ln_f%s" % tag, **_norm("ln_f"))
        logits = sym.FullyConnected(x, name="lm_head%s" % tag,
                                    **_params("lm_head", vocab_size))
        logits_outs.append(logits)
    pools_out = []
    for i in range(num_layers):
        pools_out.extend([k_pools[i], v_pools[i]])
    return sym.Group(logits_outs + pools_out)


def get_transformer_lm_catchup(vocab_size=32000, num_layers=4, num_heads=8,
                               hidden=512, max_seq_len=128, lanes=8,
                               num_pages=64, page_size=16, max_pages=8,
                               width=4):
    """Windowed teacher-forcing pass: ``width`` KNOWN tokens per lane
    advance in ONE forward over paged KV.  The tokens come from a
    prefix-cache hit's suffix, a re-admitted preemptee's transcript, or
    a speculative draft's proposals — in every case nothing has to wait
    for the previous slot's argmax, so the sequential decode chain is
    unnecessary.

    Unlike :func:`get_transformer_lm_verify` — the older construction
    that chains ``width`` literal copies of the decode block and pays
    its dispatch cost ``width`` times — this is a single causal pass:
    every projection runs batched over ``lanes * width`` rows and each
    layer gathers the paged history once
    (``_contrib_PagedAttentionWindow``), so the cost scales like a
    short prefill instead of ``width`` decode steps.  It writes the
    same K/V slots and attends the same masked history, and the
    engine's parity tests assert transcript equality against plain
    decode.

    Inputs: ``data`` (lanes, width) token ids; ``positions``
    (lanes, width) absolute positions (pad slots at
    ``max_seq_len - 1`` with a zero page-table row park in scratch);
    ``page_table`` (lanes, max_pages); per-layer pools.  Output:
    ``Group([logits, k_pool0_out, v_pool0_out, ...])`` with logits
    (lanes * width, vocab) — row ``lane * width + w`` scores window
    slot ``w``."""
    head_dim = hidden // num_heads
    data = sym.Variable("data")
    positions = sym.Variable("positions")
    page_table = sym.Variable("page_table")
    pos_tab = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    tok = sym.Reshape(data, shape=(-1,), name="tok_flat")
    x = sym.Embedding(tok, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    pe = sym.Reshape(pos_tab, shape=(max_seq_len, hidden), name="pos_flat")
    pos_flat = sym.Reshape(positions, shape=(-1,), name="pos_ids_flat")
    pe = sym.take(pe, pos_flat, name="pos_take")  # (lanes*width, hidden)
    x = sym.broadcast_add(x, pe, name="pos_add")
    pools_out = []
    for i in range(num_layers):
        name = "layer%d" % i
        h = sym.LayerNorm(x, name="%s_ln1" % name)
        qkv = sym.FullyConnected(h, num_hidden=3 * hidden,
                                 name="%s_qkv" % name)
        qkv = sym.Reshape(qkv, shape=(-1, 3, num_heads, head_dim))
        q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=1,
                                   squeeze_axis=True, name="%s_split" % name)
        k_pool = sym.Variable("%s_k_pool" % name)
        v_pool = sym.Variable("%s_v_pool" % name)
        att, k_out, v_out = sym._contrib_PagedAttentionWindow(
            q, k, v, k_pool, v_pool, page_table, positions,
            page_size=page_size, name="%s_attn" % name)
        pools_out.extend([k_out, v_out])
        att = sym.Reshape(att, shape=(-1, hidden))
        proj = sym.FullyConnected(att, num_hidden=hidden,
                                  name="%s_proj" % name)
        x = sym.broadcast_add(x, proj, name="%s_res1" % name)
        h = sym.LayerNorm(x, name="%s_ln2" % name)
        h = sym.FullyConnected(h, num_hidden=4 * hidden,
                               name="%s_fc1" % name)
        h = sym.gelu(h, name="%s_gelu" % name)
        h = sym.FullyConnected(h, num_hidden=hidden, name="%s_fc2" % name)
        x = sym.broadcast_add(x, h, name="%s_res2" % name)
    x = sym.LayerNorm(x, name="ln_f")
    logits = sym.FullyConnected(x, num_hidden=vocab_size, name="lm_head")
    return sym.Group([logits] + pools_out)


def get_transformer_lm_decode(vocab_size=32000, num_layers=4, num_heads=8,
                              hidden=512, max_seq_len=128, lanes=8,
                              num_pages=64, page_size=16, max_pages=8):
    """One incremental decode step over paged KV: ``lanes`` sequences
    advance one token each, reading/writing fixed-size KV pages through
    per-lane page tables instead of recomputing the prefix.

    Inputs: ``data`` (lanes,) current token ids; ``positions`` (lanes,)
    absolute positions; ``page_table`` (lanes, max_pages);
    ``layer%d_k_pool`` / ``layer%d_v_pool`` (num_pages, page_size,
    heads, head_dim) per layer.  Output: ``Group([logits, k_pool0_out,
    v_pool0_out, ...])`` with logits (lanes, vocab).  Everything is
    static-shape, so one executable per lane count serves any mix of
    sequence lengths — the continuous-batching contract."""
    head_dim = hidden // num_heads
    data = sym.Variable("data")
    positions = sym.Variable("positions")
    page_table = sym.Variable("page_table")
    pos_tab = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    pe = sym.Reshape(pos_tab, shape=(max_seq_len, hidden), name="pos_flat")
    pe = sym.take(pe, positions, name="pos_take")  # (lanes, hidden)
    x = sym.broadcast_add(x, pe, name="pos_add")
    pools_out = []
    for i in range(num_layers):
        name = "layer%d" % i
        h = sym.LayerNorm(x, name="%s_ln1" % name)
        qkv = sym.FullyConnected(h, num_hidden=3 * hidden,
                                 name="%s_qkv" % name)
        qkv = sym.Reshape(qkv, shape=(-1, 3, num_heads, head_dim))
        q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=1,
                                   squeeze_axis=True, name="%s_split" % name)
        k_pool = sym.Variable("%s_k_pool" % name)
        v_pool = sym.Variable("%s_v_pool" % name)
        att, k_out, v_out = sym._contrib_PagedAttention(
            q, k, v, k_pool, v_pool, page_table, positions,
            page_size=page_size, name="%s_attn" % name)
        pools_out.extend([k_out, v_out])
        att = sym.Reshape(att, shape=(-1, hidden))
        proj = sym.FullyConnected(att, num_hidden=hidden,
                                  name="%s_proj" % name)
        x = sym.broadcast_add(x, proj, name="%s_res1" % name)
        h = sym.LayerNorm(x, name="%s_ln2" % name)
        h = sym.FullyConnected(h, num_hidden=4 * hidden,
                               name="%s_fc1" % name)
        h = sym.gelu(h, name="%s_gelu" % name)
        h = sym.FullyConnected(h, num_hidden=hidden, name="%s_fc2" % name)
        x = sym.broadcast_add(x, h, name="%s_res2" % name)
    x = sym.LayerNorm(x, name="ln_f")
    logits = sym.FullyConnected(x, num_hidden=vocab_size, name="lm_head")
    return sym.Group([logits] + pools_out)

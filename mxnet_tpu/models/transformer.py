"""Decoder-only transformer LM symbol builder — the TPU-native flagship
model family (beyond the 2017 reference, which predates transformers; its
sequence-model slot was the RNN stack, rnn/rnn_cell.py).

Rides the framework's high-MFU path: attention through the Pallas
flash-attention kernels (``_contrib_FlashAttention``, fwd+bwd, K/V
streamed — ops/attention.py), all matmuls MXU-shaped, pre-norm residual
blocks with LayerNorm/gelu. Sequence parallelism for longer-than-HBM
contexts lives in ``parallel.ring`` / ``parallel.mesh``.
"""

from .. import symbol as sym


def _dense(x, n_in, n_out, name):
    """FC over the trailing dim of a (b, s, d) tensor (FullyConnected is
    2-D, reference fully_connected-inl.h): reshape to rows and back."""
    h = sym.Reshape(x, shape=(-1, n_in))
    h = sym.FullyConnected(h, num_hidden=n_out, name=name)
    return h


def _block(x, hidden, num_heads, seq_len, name, block_q=512, block_k=512,
           attn_impl="flash"):
    head_dim = hidden // num_heads
    # attention sublayer (pre-norm)
    h = sym.LayerNorm(x, name="%s_ln1" % name)
    qkv = _dense(h, hidden, 3 * hidden, "%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, head_dim))
    q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=2, squeeze_axis=True,
                               name="%s_split" % name)
    if attn_impl == "splash":
        # upstream splash kernel (ops/attention.py splash_attention) —
        # the A/B alternative to the in-tree flash kernels
        att = sym._contrib_SplashAttention(q, k, v, causal=True,
                                           name="%s_attn" % name)
    elif attn_impl == "flash":
        att = sym._contrib_FlashAttention(q, k, v, causal=True,
                                          block_q=block_q, block_k=block_k,
                                          name="%s_attn" % name)
    else:
        raise ValueError("attn_impl must be 'flash' or 'splash', got %r"
                         % (attn_impl,))
    att = sym.Reshape(att, shape=(-1, seq_len, hidden))
    proj = _dense(att, hidden, hidden, "%s_proj" % name)
    x = sym.broadcast_add(x, sym.Reshape(proj, shape=(-1, seq_len, hidden)),
                          name="%s_res1" % name)
    # mlp sublayer (pre-norm, gelu)
    h = sym.LayerNorm(x, name="%s_ln2" % name)
    h = _dense(h, hidden, 4 * hidden, "%s_fc1" % name)
    h = sym.gelu(h, name="%s_gelu" % name)
    h = _dense(h, 4 * hidden, hidden, "%s_fc2" % name)
    return sym.broadcast_add(x, sym.Reshape(h, shape=(-1, seq_len, hidden)),
                             name="%s_res2" % name)


def get_transformer_lm(vocab_size=32000, num_layers=4, num_heads=8,
                       hidden=512, seq_len=128, block_q=512, block_k=512,
                       attn_impl="flash"):
    """Causal LM: data (b, seq_len) token ids -> SoftmaxOutput over the
    vocab at every position (label (b*seq_len,) next-token ids).
    ``attn_impl``: "flash" (in-tree Pallas kernels) or "splash"
    (upstream jax splash attention)."""
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(1, seq_len, hidden))
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    x = sym.broadcast_add(x, pos, name="pos_add")
    for i in range(num_layers):
        x = _block(x, hidden, num_heads, seq_len, "layer%d" % i,
                   block_q=block_q, block_k=block_k, attn_impl=attn_impl)
    x = sym.LayerNorm(x, name="ln_f")
    logits = _dense(x, hidden, vocab_size, "lm_head")  # (b*s, vocab)
    # label arrives (b, seq_len) from the iterator; flatten inside the
    # symbol like the reference LM examples (example/rnn/lstm_bucketing.py)
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(logits, label=label, name="softmax")

"""Decoder-only transformer LM symbol builder — the TPU-native flagship
model family (beyond the 2017 reference, which predates transformers; its
sequence-model slot was the RNN stack, rnn/rnn_cell.py).

Rides the framework's high-MFU path: attention through the Pallas
flash-attention kernels (``_contrib_FlashAttention``, fwd+bwd, K/V
streamed — ops/attention.py), all matmuls MXU-shaped, pre-norm residual
blocks with LayerNorm/gelu. Sequence parallelism for longer-than-HBM
contexts lives in ``parallel.ring`` / ``parallel.mesh``.
"""

from .. import symbol as sym


def _dense(x, n_in, n_out, name):
    """FC over the trailing dim of a (b, s, d) tensor (FullyConnected is
    2-D, reference fully_connected-inl.h): reshape to rows and back."""
    h = sym.Reshape(x, shape=(-1, n_in))
    h = sym.FullyConnected(h, num_hidden=n_out, name=name)
    return h


def _block(x, hidden, num_heads, seq_len, name, block_q=None, block_k=None,
           attn_impl="flash"):
    head_dim = hidden // num_heads
    # attention sublayer (pre-norm)
    h = sym.LayerNorm(x, name="%s_ln1" % name)
    qkv = _dense(h, hidden, 3 * hidden, "%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, head_dim))
    q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=2, squeeze_axis=True,
                               name="%s_split" % name)
    if attn_impl == "splash":
        # upstream splash kernel (ops/attention.py splash_attention) —
        # the A/B alternative to the in-tree flash kernels
        att = sym._contrib_SplashAttention(q, k, v, causal=True,
                                           name="%s_attn" % name)
    elif attn_impl == "flash":
        att = sym._contrib_FlashAttention(q, k, v, causal=True,
                                          block_q=block_q, block_k=block_k,
                                          name="%s_attn" % name)
    else:
        raise ValueError("attn_impl must be 'flash' or 'splash', got %r"
                         % (attn_impl,))
    att = sym.Reshape(att, shape=(-1, seq_len, hidden))
    proj = _dense(att, hidden, hidden, "%s_proj" % name)
    x = sym.broadcast_add(x, sym.Reshape(proj, shape=(-1, seq_len, hidden)),
                          name="%s_res1" % name)
    # mlp sublayer (pre-norm, gelu)
    h = sym.LayerNorm(x, name="%s_ln2" % name)
    h = _dense(h, hidden, 4 * hidden, "%s_fc1" % name)
    h = sym.gelu(h, name="%s_gelu" % name)
    h = _dense(h, 4 * hidden, hidden, "%s_fc2" % name)
    return sym.broadcast_add(x, sym.Reshape(h, shape=(-1, seq_len, hidden)),
                             name="%s_res2" % name)


def get_transformer_lm(vocab_size=32000, num_layers=4, num_heads=8,
                       hidden=512, seq_len=128, block_q=None, block_k=None,
                       attn_impl="flash"):
    """Causal LM: data (b, seq_len) token ids -> SoftmaxOutput over the
    vocab at every position (label (b*seq_len,) next-token ids).
    ``attn_impl``: "flash" (in-tree Pallas kernels) or "splash"
    (upstream jax splash attention)."""
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(1, seq_len, hidden))
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    x = sym.broadcast_add(x, pos, name="pos_add")
    for i in range(num_layers):
        x = _block(x, hidden, num_heads, seq_len, "layer%d" % i,
                   block_q=block_q, block_k=block_k, attn_impl=attn_impl)
    x = sym.LayerNorm(x, name="ln_f")
    logits = _dense(x, hidden, vocab_size, "lm_head")  # (b*s, vocab)
    # label arrives (b, seq_len) from the iterator; flatten inside the
    # symbol like the reference LM examples (example/rnn/lstm_bucketing.py)
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(logits, label=label, name="softmax")


# ---------------------------------------------------------------------------
# Generative-serving variants (mxnet_tpu.generation) — same weight names as
# get_transformer_lm, so one trained checkpoint binds all three symbols.
# ---------------------------------------------------------------------------


def _prefill_block(x, hidden, num_heads, seq_len, name, attn_impl):
    """Pre-norm block that also RETURNS its (k, v) projections — the
    prefill pass feeds them into the paged KV pool so decode never
    recomputes the prefix."""
    head_dim = hidden // num_heads
    h = sym.LayerNorm(x, name="%s_ln1" % name)
    qkv = _dense(h, hidden, 3 * hidden, "%s_qkv" % name)
    qkv = sym.Reshape(qkv, shape=(-1, seq_len, 3, num_heads, head_dim))
    q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=2, squeeze_axis=True,
                               name="%s_split" % name)
    if attn_impl == "dense":
        # dense oracle attention: prefill runs once per sequence and must
        # be CPU-fast (interpret-mode Pallas is not), TPU still fuses it
        att = sym._contrib_DenseAttention(q, k, v, causal=True,
                                          name="%s_attn" % name)
    elif attn_impl == "flash":
        att = sym._contrib_FlashAttention(q, k, v, causal=True,
                                          name="%s_attn" % name)
    else:
        raise ValueError("attn_impl must be 'dense' or 'flash', got %r"
                         % (attn_impl,))
    att = sym.Reshape(att, shape=(-1, seq_len, hidden))
    proj = _dense(att, hidden, hidden, "%s_proj" % name)
    x = sym.broadcast_add(x, sym.Reshape(proj, shape=(-1, seq_len, hidden)),
                          name="%s_res1" % name)
    h = sym.LayerNorm(x, name="%s_ln2" % name)
    h = _dense(h, hidden, 4 * hidden, "%s_fc1" % name)
    h = sym.gelu(h, name="%s_gelu" % name)
    h = _dense(h, 4 * hidden, hidden, "%s_fc2" % name)
    x = sym.broadcast_add(x, sym.Reshape(h, shape=(-1, seq_len, hidden)),
                          name="%s_res2" % name)
    return x, k, v


def get_transformer_lm_prefill(vocab_size=32000, num_layers=4, num_heads=8,
                               hidden=512, seq_len=128, max_seq_len=None,
                               attn_impl="dense"):
    """Prefill pass for generation: ``data`` (b, seq_len) token ids ->
    ``Group([logits, k0, v0, k1, v1, ...])`` with logits (b, seq_len,
    vocab) and per-layer K/V (b, seq_len, heads, head_dim).

    ``seq_len`` is this executable's (bucketed) prompt capacity;
    ``max_seq_len`` (default ``seq_len``) is the position-table capacity
    shared with the training symbol — the engine builds one prefill
    executor per length bucket against one ``pos_embed_weight``.
    Prompts shorter than ``seq_len`` are right-padded by the caller;
    causal attention keeps the padding from contaminating real
    positions, so only outputs at < length are meaningful."""
    if max_seq_len is None:
        max_seq_len = seq_len
    data = sym.Variable("data")
    pos = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    if seq_len != max_seq_len:
        pos = sym.slice_axis(pos, axis=1, begin=0, end=seq_len,
                             name="pos_slice")
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    x = sym.broadcast_add(x, pos, name="pos_add")
    kvs = []
    for i in range(num_layers):
        x, k, v = _prefill_block(x, hidden, num_heads, seq_len,
                                 "layer%d" % i, attn_impl)
        kvs.extend([k, v])
    x = sym.LayerNorm(x, name="ln_f")
    logits = _dense(x, hidden, vocab_size, "lm_head")
    logits = sym.Reshape(logits, shape=(-1, seq_len, vocab_size),
                         name="logits")
    return sym.Group([logits] + kvs)


def get_transformer_lm_decode(vocab_size=32000, num_layers=4, num_heads=8,
                              hidden=512, max_seq_len=128, lanes=8,
                              num_pages=64, page_size=16, max_pages=8):
    """One incremental decode step over paged KV: ``lanes`` sequences
    advance one token each, reading/writing fixed-size KV pages through
    per-lane page tables instead of recomputing the prefix.

    Inputs: ``data`` (lanes,) current token ids; ``positions`` (lanes,)
    absolute positions; ``page_table`` (lanes, max_pages);
    ``layer%d_k_pool`` / ``layer%d_v_pool`` (num_pages, page_size,
    heads, head_dim) per layer.  Output: ``Group([logits, k_pool0_out,
    v_pool0_out, ...])`` with logits (lanes, vocab).  Everything is
    static-shape, so one executable per lane count serves any mix of
    sequence lengths — the continuous-batching contract."""
    head_dim = hidden // num_heads
    data = sym.Variable("data")
    positions = sym.Variable("positions")
    page_table = sym.Variable("page_table")
    pos_tab = sym.Variable("pos_embed_weight", shape=(1, max_seq_len, hidden))
    x = sym.Embedding(data, input_dim=vocab_size, output_dim=hidden,
                      name="tok_embed")
    pe = sym.Reshape(pos_tab, shape=(max_seq_len, hidden), name="pos_flat")
    pe = sym.take(pe, positions, name="pos_take")  # (lanes, hidden)
    x = sym.broadcast_add(x, pe, name="pos_add")
    pools_out = []
    for i in range(num_layers):
        name = "layer%d" % i
        h = sym.LayerNorm(x, name="%s_ln1" % name)
        qkv = sym.FullyConnected(h, num_hidden=3 * hidden,
                                 name="%s_qkv" % name)
        qkv = sym.Reshape(qkv, shape=(-1, 3, num_heads, head_dim))
        q, k, v = sym.SliceChannel(qkv, num_outputs=3, axis=1,
                                   squeeze_axis=True, name="%s_split" % name)
        k_pool = sym.Variable("%s_k_pool" % name)
        v_pool = sym.Variable("%s_v_pool" % name)
        att, k_out, v_out = sym._contrib_PagedAttention(
            q, k, v, k_pool, v_pool, page_table, positions,
            page_size=page_size, name="%s_attn" % name)
        pools_out.extend([k_out, v_out])
        att = sym.Reshape(att, shape=(-1, hidden))
        proj = sym.FullyConnected(att, num_hidden=hidden,
                                  name="%s_proj" % name)
        x = sym.broadcast_add(x, proj, name="%s_res1" % name)
        h = sym.LayerNorm(x, name="%s_ln2" % name)
        h = sym.FullyConnected(h, num_hidden=4 * hidden,
                               name="%s_fc1" % name)
        h = sym.gelu(h, name="%s_gelu" % name)
        h = sym.FullyConnected(h, num_hidden=hidden, name="%s_fc2" % name)
        x = sym.broadcast_add(x, h, name="%s_res2" % name)
    x = sym.LayerNorm(x, name="ln_f")
    logits = sym.FullyConnected(x, num_hidden=vocab_size, name="lm_head")
    return sym.Group([logits] + pools_out)

"""LeNet-5 style conv net for MNIST (reference
example/image-classification/train_mnist.py get_lenet capability)."""

from .. import symbol as sym


def get_lenet(num_classes=10):
    data = sym.Variable("data")
    net = sym.Convolution(data=data, kernel=(5, 5), num_filter=20, name="conv1")
    net = sym.Activation(data=net, act_type="tanh")
    net = sym.Pooling(data=net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.Convolution(data=net, kernel=(5, 5), num_filter=50, name="conv2")
    net = sym.Activation(data=net, act_type="tanh")
    net = sym.Pooling(data=net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=500, name="fc1")
    net = sym.Activation(data=net, act_type="tanh")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")

"""DLRM / two-tower recommender bench model (the classic MXNet sparse
workload): per-slot embedding bags over vocabularies that dwarf device
memory + a dense-feature MLP tower, concatenated into a top MLP with a
logistic CTR head.

The embedding weights are ``stype='row_sparse'`` slots routed through the
sparse parameter plane: each Embedding binds ``input_dim=capacity`` (the
max distinct rows one batch touches), NOT the vocabulary —
SparseEmbeddingModule remaps ids per batch and pulls only the touched
rows from the server-sharded table (docs/how_to/sparse.md).

``get_dlrm`` returns ``(symbol, sparse_slots)`` — the symbol and the
matching SparseEmbeddingModule routing config are built together so the
capacity/input_dim invariant cannot drift.
"""

from .. import symbol as sym

__all__ = ["get_dlrm"]


def get_dlrm(num_slots=4, vocab_sizes=None, embed_dim=16, capacity=256,
             bag_len=8, dense_dim=13, bottom_hidden=(64, 16),
             top_hidden=(64, 32), init=("uniform", 0.01)):
    """Build the DLRM symbol + row_sparse slot config.

    Inputs: ``dense`` (batch, dense_dim) float features and one
    ``slot<i>_indices`` (batch, bag_len) id array per slot (multi-hot
    bags, sum-pooled).  Label: ``ctr_label`` (batch,) clicks.
    """
    if vocab_sizes is None:
        vocab_sizes = [100000] * num_slots
    if len(vocab_sizes) != num_slots:
        raise ValueError("need one vocab size per slot")

    # bottom (dense) tower
    net = sym.Variable("dense")
    for i, h in enumerate(bottom_hidden):
        net = sym.FullyConnected(data=net, num_hidden=h,
                                 name="bot_fc%d" % i)
        net = sym.Activation(data=net, act_type="relu",
                             name="bot_relu%d" % i)
    towers = [net]

    # sparse towers: Embedding bound at capacity rows, sum-pooled bags
    sparse_slots = {}
    for i, vocab in enumerate(vocab_sizes):
        name = "slot%d" % i
        ids = sym.Variable("%s_indices" % name)
        emb = sym.Embedding(data=ids, input_dim=capacity,
                            output_dim=embed_dim,
                            name="%s_embed" % name)
        towers.append(sym.sum(emb, axis=1, name="%s_bag" % name))
        sparse_slots[name] = {
            "data": "%s_indices" % name,
            "weight": "%s_embed_weight" % name,
            "num_rows": int(vocab),
            "capacity": int(capacity),
            "init": tuple(init),
        }

    net = sym.Concat(*towers, num_args=len(towers), dim=1, name="interact")
    for i, h in enumerate(top_hidden):
        net = sym.FullyConnected(data=net, num_hidden=h,
                                 name="top_fc%d" % i)
        net = sym.Activation(data=net, act_type="relu",
                             name="top_relu%d" % i)
    net = sym.FullyConnected(data=net, num_hidden=1, name="ctr_fc")
    net = sym.LogisticRegressionOutput(data=net, name="ctr")
    return net, sparse_slots

"""Inception-v3 symbol builder.

Capability parity with reference
example/image-classification/symbols/inception-v3.py (299x299 input);
architecture per Szegedy et al., "Rethinking the Inception Architecture
for Computer Vision" (arXiv:1512.00567). Built from the paper's block
descriptions in this package's builder style.
"""

from .. import symbol as sym


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=""):
    net = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                          stride=stride, pad=pad, no_bias=True,
                          name="%s_conv" % name)
    net = sym.BatchNorm(data=net, fix_gamma=True, eps=2e-5,
                        name="%s_bn" % name)
    return sym.Activation(data=net, act_type="relu", name="%s_relu" % name)


def _pool(data, kernel, stride, pad, pool_type, name):
    return sym.Pooling(data=data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


def _block_a(data, pool_proj, name):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool-proj branches."""
    b1 = _conv(data, 64, name="%s_1x1" % name)
    b5 = _conv(data, 48, name="%s_5x5r" % name)
    b5 = _conv(b5, 64, kernel=(5, 5), pad=(2, 2), name="%s_5x5" % name)
    b3 = _conv(data, 64, name="%s_3x3r" % name)
    b3 = _conv(b3, 96, kernel=(3, 3), pad=(1, 1), name="%s_3x3a" % name)
    b3 = _conv(b3, 96, kernel=(3, 3), pad=(1, 1), name="%s_3x3b" % name)
    bp = _pool(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    bp = _conv(bp, pool_proj, name="%s_proj" % name)
    return sym.Concat(b1, b5, b3, bp, name="%s_concat" % name)


def _block_b(data, name):
    """Grid reduction 35x35 -> 17x17."""
    b3 = _conv(data, 384, kernel=(3, 3), stride=(2, 2), name="%s_3x3" % name)
    bd = _conv(data, 64, name="%s_d3x3r" % name)
    bd = _conv(bd, 96, kernel=(3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    bd = _conv(bd, 96, kernel=(3, 3), stride=(2, 2), name="%s_d3x3b" % name)
    bp = _pool(data, (3, 3), (2, 2), (0, 0), "max", "%s_pool" % name)
    return sym.Concat(b3, bd, bp, name="%s_concat" % name)


def _block_c(data, c7, name):
    """17x17 block with factorized 7x7 (1x7 then 7x1) branches."""
    b1 = _conv(data, 192, name="%s_1x1" % name)
    b7 = _conv(data, c7, name="%s_7x7r" % name)
    b7 = _conv(b7, c7, kernel=(1, 7), pad=(0, 3), name="%s_7x7a" % name)
    b7 = _conv(b7, 192, kernel=(7, 1), pad=(3, 0), name="%s_7x7b" % name)
    bd = _conv(data, c7, name="%s_d7r" % name)
    bd = _conv(bd, c7, kernel=(7, 1), pad=(3, 0), name="%s_d7a" % name)
    bd = _conv(bd, c7, kernel=(1, 7), pad=(0, 3), name="%s_d7b" % name)
    bd = _conv(bd, c7, kernel=(7, 1), pad=(3, 0), name="%s_d7c" % name)
    bd = _conv(bd, 192, kernel=(1, 7), pad=(0, 3), name="%s_d7d" % name)
    bp = _pool(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    bp = _conv(bp, 192, name="%s_proj" % name)
    return sym.Concat(b1, b7, bd, bp, name="%s_concat" % name)


def _block_d(data, name):
    """Grid reduction 17x17 -> 8x8."""
    b3 = _conv(data, 192, name="%s_3x3r" % name)
    b3 = _conv(b3, 320, kernel=(3, 3), stride=(2, 2), name="%s_3x3" % name)
    b7 = _conv(data, 192, name="%s_7x7r" % name)
    b7 = _conv(b7, 192, kernel=(1, 7), pad=(0, 3), name="%s_7x7a" % name)
    b7 = _conv(b7, 192, kernel=(7, 1), pad=(3, 0), name="%s_7x7b" % name)
    b7 = _conv(b7, 192, kernel=(3, 3), stride=(2, 2), name="%s_7x7c" % name)
    bp = _pool(data, (3, 3), (2, 2), (0, 0), "max", "%s_pool" % name)
    return sym.Concat(b3, b7, bp, name="%s_concat" % name)


def _block_e(data, pool_type, name):
    """8x8 block with expanded (split 1x3 / 3x1) branches."""
    b1 = _conv(data, 320, name="%s_1x1" % name)
    b3 = _conv(data, 384, name="%s_3x3r" % name)
    b3a = _conv(b3, 384, kernel=(1, 3), pad=(0, 1), name="%s_3x3a" % name)
    b3b = _conv(b3, 384, kernel=(3, 1), pad=(1, 0), name="%s_3x3b" % name)
    bd = _conv(data, 448, name="%s_d3r" % name)
    bd = _conv(bd, 384, kernel=(3, 3), pad=(1, 1), name="%s_d3" % name)
    bda = _conv(bd, 384, kernel=(1, 3), pad=(0, 1), name="%s_d3a" % name)
    bdb = _conv(bd, 384, kernel=(3, 1), pad=(1, 0), name="%s_d3b" % name)
    bp = _pool(data, (3, 3), (1, 1), (1, 1), pool_type, "%s_pool" % name)
    bp = _conv(bp, 192, name="%s_proj" % name)
    return sym.Concat(b1, b3a, b3b, bda, bdb, bp, name="%s_concat" % name)


def get_inception_v3(num_classes=1000):
    """Inception-v3 for 3x299x299 inputs -> SoftmaxOutput symbol."""
    data = sym.Variable("data")
    # stem: 299 -> 35
    net = _conv(data, 32, kernel=(3, 3), stride=(2, 2), name="stem1")
    net = _conv(net, 32, kernel=(3, 3), name="stem2")
    net = _conv(net, 64, kernel=(3, 3), pad=(1, 1), name="stem3")
    net = _pool(net, (3, 3), (2, 2), (0, 0), "max", "stem_pool1")
    net = _conv(net, 80, name="stem4")
    net = _conv(net, 192, kernel=(3, 3), name="stem5")
    net = _pool(net, (3, 3), (2, 2), (0, 0), "max", "stem_pool2")
    # 35x35
    net = _block_a(net, 32, "mixed0")
    net = _block_a(net, 64, "mixed1")
    net = _block_a(net, 64, "mixed2")
    net = _block_b(net, "mixed3")
    # 17x17
    net = _block_c(net, 128, "mixed4")
    net = _block_c(net, 160, "mixed5")
    net = _block_c(net, 160, "mixed6")
    net = _block_c(net, 192, "mixed7")
    net = _block_d(net, "mixed8")
    # 8x8
    net = _block_e(net, "avg", "mixed9")
    net = _block_e(net, "max", "mixed10")
    net = sym.Pooling(data=net, kernel=(8, 8), global_pool=True,
                      pool_type="avg", name="global_pool")
    net = sym.Flatten(data=net, name="flatten")
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=net, name="softmax")

"""SSD single-shot detector (BASELINE config #4).

Symbol-level port of the reference SSD graph structure
(/root/reference/example/ssd/symbol/symbol_builder.py semantics: body →
multi-scale feature maps → per-scale loc/conf heads + MultiBoxPrior anchors →
MultiBoxTarget matching → SoftmaxOutput cls loss + smooth-L1 loc loss;
detection graph swaps the losses for MultiBoxDetection NMS). The backbone
here is a compact conv body rather than VGG16_reduced — the graph topology,
target encoding and loss wiring match; swap the body for parity-scale runs.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_ssd_train", "get_ssd_detect", "get_ssd_symbols"]


def _conv_block(data, num_filter, name, stride=(1, 1), pool=True):
    net = sym.Convolution(data=data, num_filter=num_filter, kernel=(3, 3),
                          pad=(1, 1), stride=stride, name=name + "_conv")
    net = sym.BatchNorm(data=net, name=name + "_bn")
    net = sym.Activation(data=net, act_type="relu", name=name + "_relu")
    if pool:
        net = sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                          pool_type="max", name=name + "_pool")
    return net


def _multibox_layer(feats, num_classes, sizes, ratios):
    """Per-scale heads; returns (loc_preds, cls_preds, anchors) with the
    reference layouts: loc (b, A*4), cls (b, num_cls+1, A), anchors
    (1, A, 4)."""
    loc_layers = []
    cls_layers = []
    anchor_layers = []
    num_cls_total = num_classes + 1  # background class 0
    for i, feat in enumerate(feats):
        na = len(sizes[i]) + len(ratios[i]) - 1
        loc = sym.Convolution(data=feat, num_filter=na * 4, kernel=(3, 3),
                              pad=(1, 1), name="loc_pred_%d" % i)
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_layers.append(sym.Flatten(loc))
        cls = sym.Convolution(data=feat, num_filter=na * num_cls_total,
                              kernel=(3, 3), pad=(1, 1),
                              name="cls_pred_%d" % i)
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_layers.append(sym.Flatten(cls))
        anchors = sym._contrib_MultiBoxPrior(
            feat, sizes=tuple(sizes[i]), ratios=tuple(ratios[i]),
            name="anchors_%d" % i)
        anchor_layers.append(sym.Reshape(anchors, shape=(0, -1, 4)))
    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(cls_preds, shape=(0, -1, num_cls_total))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchors = sym.Concat(*anchor_layers, dim=1, name="multibox_anchors")
    return loc_preds, cls_preds, anchors


def _ssd_graph(num_classes, num_filters):
    data = sym.Variable("data")
    # body: three downsampling blocks; heads tap the last three maps
    net = _conv_block(data, num_filters[0], "b1")          # stride 2
    f1 = _conv_block(net, num_filters[1], "b2")            # stride 4
    f2 = _conv_block(f1, num_filters[2], "b3")             # stride 8
    f3 = _conv_block(f2, num_filters[3], "b4")             # stride 16
    feats = [f1, f2, f3]
    sizes = [(0.2, 0.272), (0.37, 0.447), (0.54, 0.619)]
    ratios = [(1.0, 2.0, 0.5)] * 3
    return data, _multibox_layer(feats, num_classes, sizes, ratios)


def get_ssd_train(num_classes=20, num_filters=(16, 32, 64, 64)):
    """Training symbol: outputs [cls_prob, loc_loss, cls_label]
    (reference symbol_builder.get_symbol_train)."""
    label = sym.Variable("label")
    _, (loc_preds, cls_preds, anchors) = _ssd_graph(num_classes, num_filters)
    tmp = sym._contrib_MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1, negative_mining_ratio=3.0,
        minimum_negative_samples=0, negative_mining_thresh=0.5,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    loc_target = tmp[0]
    loc_target_mask = tmp[1]
    cls_target = tmp[2]
    cls_prob = sym.SoftmaxOutput(data=cls_preds, label=cls_target,
                                 ignore_label=-1.0, use_ignore=True,
                                 multi_output=True, normalization="valid",
                                 name="cls_prob")
    loc_diff = loc_target_mask * (loc_preds - loc_target)
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_diff, scalar=1.0),
                            grad_scale=1.0, normalization="valid",
                            name="loc_loss")
    cls_label = sym.MakeLoss(data=cls_target, grad_scale=0.0,
                             name="cls_label")
    return sym.Group([cls_prob, loc_loss, cls_label])


def get_ssd_detect(num_classes=20, num_filters=(16, 32, 64, 64),
                   nms_thresh=0.5, force_suppress=False, nms_topk=400):
    """Inference symbol: MultiBoxDetection output (b, A, 6) rows
    [cls_id, score, xmin, ymin, xmax, ymax]."""
    _, (loc_preds, cls_preds, anchors) = _ssd_graph(num_classes, num_filters)
    cls_prob = sym.SoftmaxActivation(data=cls_preds, mode="channel",
                                     name="cls_prob")
    return sym._contrib_MultiBoxDetection(
        cls_prob, loc_preds, anchors, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=nms_topk)


def get_ssd_symbols(num_classes=20, **kwargs):
    return (get_ssd_train(num_classes, **kwargs),
            get_ssd_detect(num_classes, **kwargs))

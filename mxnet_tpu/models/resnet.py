"""ResNet v1 (He et al. 2015) symbol builder.

Capability parity with reference example/image-classification/symbols/resnet.py
(the north-star benchmark model, BASELINE.md ResNet-50) — written fresh for
TPU: 3x3/1x1 convs stay in NCHW at the symbol level and XLA lays them out for
the MXU; bottleneck widths are multiples of 128 so bf16 matmul tiles are full.
"""

from .. import symbol as sym


def _conv_bn_act(data, num_filter, kernel, stride, pad, name, act=True):
    net = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                          stride=stride, pad=pad, no_bias=True,
                          name=name + "_conv")
    net = sym.BatchNorm(data=net, fix_gamma=False, eps=2e-5, momentum=0.9,
                        name=name + "_bn")
    if act:
        net = sym.Activation(data=net, act_type="relu", name=name + "_relu")
    return net


def _bottleneck(data, num_filter, stride, dim_match, name):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut when shapes
    change (resnet-50/101/152 unit)."""
    net = _conv_bn_act(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                       name + "_a")
    net = _conv_bn_act(net, num_filter // 4, (3, 3), stride, (1, 1),
                       name + "_b")
    net = _conv_bn_act(net, num_filter, (1, 1), (1, 1), (0, 0), name + "_c",
                       act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_act(data, num_filter, (1, 1), stride, (0, 0),
                                name + "_sc", act=False)
    return sym.Activation(data=net + shortcut, act_type="relu",
                          name=name + "_out")


def _basic(data, num_filter, stride, dim_match, name):
    """3x3 -> 3x3 basic unit (resnet-18/34)."""
    net = _conv_bn_act(data, num_filter, (3, 3), stride, (1, 1), name + "_a")
    net = _conv_bn_act(net, num_filter, (3, 3), (1, 1), (1, 1), name + "_b",
                       act=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_act(data, num_filter, (1, 1), stride, (0, 0),
                                name + "_sc", act=False)
    return sym.Activation(data=net + shortcut, act_type="relu",
                          name=name + "_out")


_DEPTH_CONFIGS = {
    18: ([2, 2, 2, 2], [64, 128, 256, 512], _basic),
    34: ([3, 4, 6, 3], [64, 128, 256, 512], _basic),
    50: ([3, 4, 6, 3], [256, 512, 1024, 2048], _bottleneck),
    101: ([3, 4, 23, 3], [256, 512, 1024, 2048], _bottleneck),
    152: ([3, 8, 36, 3], [256, 512, 1024, 2048], _bottleneck),
}


def get_resnet(num_classes=1000, num_layers=50, image_shape=(3, 224, 224)):
    if num_layers not in _DEPTH_CONFIGS:
        raise ValueError("resnet depth must be one of %s"
                         % sorted(_DEPTH_CONFIGS))
    units, filters, block = _DEPTH_CONFIGS[num_layers]

    data = sym.Variable("data")
    small_image = image_shape[-1] <= 64
    if small_image:  # cifar-style stem
        net = _conv_bn_act(data, 64, (3, 3), (1, 1), (1, 1), "stem")
    else:  # imagenet stem: 7x7/2 + 3x3/2 maxpool
        net = _conv_bn_act(data, 64, (7, 7), (2, 2), (3, 3), "stem")
        net = sym.Pooling(data=net, pool_type="max", kernel=(3, 3),
                          stride=(2, 2), pad=(1, 1), name="stem_pool")

    for stage, (n_units, n_filter) in enumerate(zip(units, filters)):
        for unit in range(n_units):
            stride = (1, 1) if stage == 0 or unit > 0 else (2, 2)
            dim_match = unit > 0
            net = block(net, n_filter, stride, dim_match,
                        "stage%d_unit%d" % (stage + 1, unit + 1))

    net = sym.Pooling(data=net, global_pool=True, pool_type="avg",
                      kernel=(7, 7), name="global_pool")
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=net, name="softmax")

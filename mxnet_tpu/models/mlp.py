"""Multi-layer perceptron (reference example/image-classification/symbols/mlp.py
capability)."""

from .. import symbol as sym


def get_mlp(num_classes=10, hidden=(128, 64)):
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(data=net, num_hidden=h, name="fc%d" % (i + 1))
        net = sym.Activation(data=net, act_type="relu", name="relu%d" % (i + 1))
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc_out")
    return sym.SoftmaxOutput(data=net, name="softmax")

"""Model zoo: symbol builders for the benchmark configs
(reference: example/image-classification/symbols/*.py — capability parity,
fresh TPU-oriented implementations; NCHW layout with bf16-friendly blocks)."""

from .lenet import get_lenet
from .mlp import get_mlp
from .resnet import get_resnet
from .alexnet import get_alexnet
from .inception_bn import get_inception_bn
from .inception_v3 import get_inception_v3
from .vgg import get_vgg
from .googlenet import get_googlenet
from .ssd import get_ssd_train, get_ssd_detect, get_ssd_symbols
from .transformer import get_transformer_lm
from .dlrm import get_dlrm

__all__ = ["get_ssd_train", "get_ssd_detect", "get_ssd_symbols",
           "get_lenet", "get_mlp", "get_resnet", "get_alexnet",
           "get_inception_bn", "get_inception_v3", "get_vgg",
           "get_googlenet", "get_transformer_lm", "get_dlrm"]

"""VGG symbol builder (Simonyan & Zisserman 2014).

Capability parity with reference example/image-classification/symbols/vgg.py
(one of the benchmark model families) — written fresh: conv widths are
powers of two so bf16 MXU tiles stay full; the classifier keeps the two
4096-wide FC layers of the paper.
"""
from .. import symbol as sym

_CONFIGS = {
    11: ((64,), (128,), (256, 256), (512, 512), (512, 512)),
    13: ((64, 64), (128, 128), (256, 256), (512, 512), (512, 512)),
    16: ((64, 64), (128, 128), (256, 256, 256), (512, 512, 512),
         (512, 512, 512)),
    19: ((64, 64), (128, 128), (256, 256, 256, 256), (512, 512, 512, 512),
         (512, 512, 512, 512)),
}


def get_vgg(num_classes=1000, num_layers=16, batch_norm=False):
    if num_layers not in _CONFIGS:
        raise ValueError("vgg depth must be one of %s" % sorted(_CONFIGS))
    net = sym.Variable("data")
    for si, widths in enumerate(_CONFIGS[num_layers]):
        for ci, width in enumerate(widths):
            name = "conv%d_%d" % (si + 1, ci + 1)
            net = sym.Convolution(net, num_filter=width, kernel=(3, 3),
                                  pad=(1, 1), name=name)
            if batch_norm:
                net = sym.BatchNorm(net, fix_gamma=False, name=name + "_bn")
            net = sym.Activation(net, act_type="relu", name=name + "_relu")
        net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                          name="pool%d" % (si + 1))
    net = sym.Flatten(net)
    for i, width in enumerate((4096, 4096)):
        net = sym.FullyConnected(net, num_hidden=width, name="fc%d" % (i + 6))
        net = sym.Activation(net, act_type="relu", name="relu%d" % (i + 6))
        net = sym.Dropout(net, p=0.5, name="drop%d" % (i + 6))
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(net, name="softmax")

"""Numerics test harness — the TPU-native analogue of the reference's
``python/mxnet/test_utils.py:360-677`` (check_numeric_gradient,
check_symbolic_forward/backward, check_consistency).

Semantics match the reference harness; internals are re-designed:
the symbolic backward comes from JAX autodiff (``jax.vjp`` inside
``Executor.backward``) and the cross-backend oracle compares fp32 vs
bf16 (TPU's fast dtype) instead of the reference's cpu-vs-gpu fp16.
"""

from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, current_context
from .ndarray import NDArray
from .symbol import Symbol

__all__ = [
    "default_context", "same", "reldiff", "almost_equal",
    "assert_almost_equal", "rand_shape_nd", "rand_ndarray",
    "numeric_grad", "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "simple_forward",
    "check_speed", "DummyIter",
]

_RTOL = 1e-5
_ATOL = 1e-7


def default_context() -> Context:
    return current_context()


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b) -> bool:
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def reldiff(a, b) -> float:
    a = _as_numpy(a).astype(np.float64)
    b = _as_numpy(b).astype(np.float64)
    diff = np.abs(a - b).sum()
    norm = np.abs(a).sum() + np.abs(b).sum()
    if norm == 0:
        return 0.0 if diff == 0 else float("inf")
    return float(diff / norm)


def almost_equal(a, b, rtol=_RTOL, atol=_ATOL) -> bool:
    return np.allclose(_as_numpy(a), _as_numpy(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=_RTOL, atol=_ATOL, names=("a", "b")):
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    if not np.allclose(a_np, b_np, rtol=rtol, atol=atol):
        idx = np.unravel_index(
            np.argmax(np.abs(a_np.astype(np.float64) - b_np.astype(np.float64))),
            a_np.shape) if a_np.shape else ()
        raise AssertionError(
            "Arrays %s, %s not almost equal (rtol=%g atol=%g); worst at %s: "
            "%r vs %r" % (names[0], names[1], rtol, atol, idx,
                          a_np[idx] if a_np.shape else a_np,
                          b_np[idx] if b_np.shape else b_np))


def rand_shape_nd(ndim, dim=6):
    return tuple(np.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, ctx=None, dtype=np.float32, scale=1.0):
    return nd.array(np.random.uniform(-scale, scale, size=shape).astype(dtype),
                    ctx=ctx)


# ---------------------------------------------------------------------------
# finite differences
# ---------------------------------------------------------------------------


def numeric_grad(f, arrays, eps=1e-4):
    """Central-difference gradient of scalar-valued ``f(dict_of_np)`` wrt each
    array.  Returns a dict name->grad with the same shapes."""
    arrays = {k: np.asarray(v, dtype=np.float64).copy()
              for k, v in arrays.items()}
    grads = {}
    for name, arr in arrays.items():
        g = np.zeros_like(arr)
        flat, gflat = arr.reshape(-1), g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            fp = f(arrays)
            flat[i] = orig - eps
            fm = f(arrays)
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g
    return grads


def _bind_with(sym: Symbol, location, aux_states=None, grad_req="write",
               ctx=None, dtype=np.float32):
    ctx = ctx or default_context()
    args = {k: nd.array(np.asarray(v, dtype=dtype), ctx=ctx)
            for k, v in location.items()}
    aux = None
    if aux_states:
        aux = {k: nd.array(np.asarray(v, dtype=dtype), ctx=ctx)
               for k, v in aux_states.items()}
    grads = None
    if grad_req != "null":
        grads = {k: nd.zeros(np.asarray(v).shape, ctx, dtype=dtype)
                 for k, v in location.items()}
    return sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                    aux_states=aux)


def _normalize_location(sym: Symbol, location):
    if isinstance(location, dict):
        return dict(location)
    return dict(zip(sym.list_arguments(), location))


def check_numeric_gradient(sym: Symbol, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=1e-3,
                           grad_nodes=None, ctx=None):
    """Compare ``Executor.backward`` (jax.vjp) against central differences of
    the summed outputs.  Mirrors reference ``test_utils.check_numeric_gradient``
    (finite differences vs symbolic backward)."""
    location = _normalize_location(sym, location)
    location = {k: np.asarray(v, dtype=np.float64) for k, v in location.items()}
    grad_nodes = list(grad_nodes or location.keys())

    exe = _bind_with(sym, location, aux_states, ctx=ctx)
    outs = exe.forward(is_train=True)
    head_grads = [nd.ones(o.shape, dtype='float32') for o in outs]
    exe.backward(head_grads)
    sym_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    def f(arrs):
        e = _bind_with(sym, arrs, aux_states, grad_req="null", ctx=ctx)
        outs = e.forward(is_train=True)
        return float(sum(o.asnumpy().astype(np.float64).sum() for o in outs))

    for name in grad_nodes:
        arr = location[name].copy()
        num = np.zeros_like(arr)
        flat, nflat = arr.reshape(-1), num.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = f(location | {name: arr})
            flat[i] = orig - numeric_eps
            fm = f(location | {name: arr})
            flat[i] = orig
            nflat[i] = (fp - fm) / (2 * numeric_eps)
        assert_almost_equal(sym_grads[name], num, rtol=rtol, atol=atol,
                            names=("symbolic[%s]" % name, "numeric[%s]" % name))


def check_symbolic_forward(sym: Symbol, location, expected, rtol=1e-5,
                           atol=1e-6, aux_states=None, ctx=None):
    location = _normalize_location(sym, location)
    exe = _bind_with(sym, location, aux_states, grad_req="null", ctx=ctx)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) == len(expected), \
        "output count %d != expected %d" % (len(outs), len(expected))
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("output[%d]" % i, "expected[%d]" % i))
    return outs


def check_symbolic_backward(sym: Symbol, location, out_grads, expected,
                            rtol=1e-5, atol=1e-6, aux_states=None,
                            grad_req="write", ctx=None):
    location = _normalize_location(sym, location)
    exe = _bind_with(sym, location, aux_states, grad_req=grad_req, ctx=ctx)
    exe.forward(is_train=True)
    exe.backward([nd.array(np.asarray(g, dtype=np.float32))
                  for g in out_grads])
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    for name, e in expected.items():
        assert_almost_equal(exe.grad_dict[name], e, rtol=rtol, atol=atol,
                            names=("grad[%s]" % name, "expected[%s]" % name))
    return exe.grad_dict


def check_consistency(sym: Symbol, location, dtypes=(np.float32, "bfloat16"),
                      rtol=2e-2, atol=1e-2, aux_states=None):
    """Cross-dtype oracle: run the same graph in each dtype and compare to the
    widest.  TPU-native replacement for the reference's cpu-vs-gpu/fp16
    ``check_consistency``: here the interesting pair is fp32 vs bf16."""
    location = _normalize_location(sym, location)
    results = []
    for dt in dtypes:
        exe = _bind_with(sym, location, aux_states, grad_req="null", dtype=dt)
        outs = exe.forward(is_train=False)
        results.append([o.asnumpy().astype(np.float64) for o in outs])
    base = results[0]
    for dt, res in zip(dtypes[1:], results[1:]):
        for i, (a, b) in enumerate(zip(base, res)):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=("%s[%d]" % (dtypes[0], i),
                                       "%s[%d]" % (dt, i)))
    return results


def simple_forward(sym: Symbol, ctx=None, **inputs):
    exe = _bind_with(sym, inputs, grad_req="null", ctx=ctx)
    outs = exe.forward(is_train=False)
    return outs[0] if len(outs) == 1 else outs


def check_speed(sym: Symbol, location=None, ctx=None, N=20,
                grad_req="write", typ="whole", **kwargs):
    """Average seconds per run of a symbol (reference test_utils
    check_speed): ``typ="whole"`` times forward_backward, ``"forward"``
    forward only. ``location`` maps args to arrays; when absent, shapes
    come from ``kwargs`` and inputs are random normal. The first run is
    excluded (compile)."""
    import time

    from . import ndarray as nd
    from .context import cpu as _cpu

    if typ not in ("whole", "forward"):
        raise ValueError("typ can only be whole or forward")
    rng = np.random.RandomState(0)
    if location is None:
        exe = sym.simple_bind(ctx or _cpu(), grad_req=grad_req, **kwargs)
        location = {k: rng.normal(size=arr.shape, scale=1.0)
                    .astype(np.float32) for k, arr in exe.arg_dict.items()}
    else:
        if not isinstance(location, dict):
            raise TypeError("Expect dict, got location=%r" % (location,))
        if kwargs:
            raise ValueError(
                "pass EITHER location (shapes come from its arrays) or "
                "shape kwargs, not both: %s" % sorted(kwargs))
        exe = sym.simple_bind(ctx or _cpu(), grad_req=grad_req,
                              **{k: v.shape for k, v in location.items()})
    for name, arr in location.items():
        exe.arg_dict[name][:] = arr

    if typ == "whole":
        def run():
            exe.forward(is_train=True)
            exe.backward()
    else:  # "forward", validated above
        def run():
            exe.forward(is_train=False)
    run()
    nd.waitall()
    tic = time.time()
    for _ in range(N):
        run()
    nd.waitall()
    return (time.time() - tic) / N


class DummyIter:
    """Infinite iterator repeating one batch — reference test_utils.DummyIter."""

    def __init__(self, real_iter):
        self.real_iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(iter(real_iter))

    def __iter__(self):
        return self

    def next(self):
        return self.the_batch

    __next__ = next

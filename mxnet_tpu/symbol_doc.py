"""Extra symbol documents (reference python/mxnet/symbol_doc.py) — see
ndarray_doc.py; one registry per surface, same mechanism."""
from __future__ import annotations

_EXTRA = {}


class SymbolDoc:
    """Subclass as ``class <op>(SymbolDoc): '<extra doc>'``; also carries
    the reference's debug-utility spirit (get_output_shape below)."""

    def __init_subclass__(cls):
        if cls.__doc__:
            _EXTRA[cls.__name__] = cls.__doc__

    @staticmethod
    def get_output_shape(sym, **input_shapes):
        """Dict of output name -> shape for given input shapes."""
        _, s_outputs, _ = sym.infer_shape(**input_shapes)
        return dict(zip(sym.list_outputs(), s_outputs))


def get_extra_doc(op_name):
    return _EXTRA.get(op_name, "")

"""Training-curve collection/plotting callbacks.

Capability parity with the reference's notebook callbacks
(python/mxnet/notebook/callback.py: PandasLogger + LiveLearningCurve).
The reference renders through bokeh; this build collects into plain
Python structures, renders through matplotlib when it is installed, and
always supports CSV export and a terminal sparkline — so the capability
works on headless TPU hosts too.
"""
from __future__ import annotations

import time
from typing import Dict, List

_TICKS = "▁▂▃▄▅▆▇█"


class MetricsLogger:
    """Collects per-batch and per-epoch metric values via the standard
    ``batch_end_callback`` / ``eval_end_callback`` hooks (the reference's
    PandasLogger capability, minus the hard pandas dependency)."""

    def __init__(self, frequent: int = 50):
        self.frequent = frequent
        self.train: Dict[str, List] = {}
        self.eval: Dict[str, List] = {}
        self._t0 = time.time()

    def _append(self, store, name, value, epoch, nbatch):
        store.setdefault(name, []).append(
            (time.time() - self._t0, epoch, nbatch, float(value)))

    def train_cb(self, param):
        """Use as ``batch_end_callback``."""
        if param.nbatch % self.frequent == 0 and param.eval_metric:
            for name, value in param.eval_metric.get_name_value():
                self._append(self.train, name, value, param.epoch,
                             param.nbatch)

    def eval_cb(self, param):
        """Use as ``eval_end_callback``/``eval_batch_end_callback``."""
        if param.eval_metric:
            for name, value in param.eval_metric.get_name_value():
                self._append(self.eval, name, value, param.epoch,
                             getattr(param, "nbatch", 0))

    # -- output ------------------------------------------------------------
    def values(self, name, which="train"):
        store = self.train if which == "train" else self.eval
        return [v[-1] for v in store.get(name, [])]

    def to_csv(self, path):
        with open(path, "w") as f:
            f.write("phase,metric,seconds,epoch,batch,value\n")
            for phase, store in (("train", self.train), ("eval", self.eval)):
                for name, rows in store.items():
                    for sec, epoch, nbatch, value in rows:
                        f.write("%s,%s,%.3f,%d,%d,%.6f\n"
                                % (phase, name, sec, epoch, nbatch, value))

    def sparkline(self, name, which="train", width=60):
        """Terminal rendering of a metric curve (non-finite samples —
        e.g. a metric's nan before any update — are skipped)."""
        import math

        vals = [v for v in self.values(name, which) if math.isfinite(v)]
        if not vals:
            return ""
        if width <= 1:
            vals = vals[-1:]
        elif len(vals) > width:
            stride = (len(vals) - 1) / float(width - 1)
            vals = [vals[round(i * stride)] for i in range(width)]
        lo, hi = min(vals), max(vals)
        span = (hi - lo) or 1.0
        return "".join(
            _TICKS[int((v - lo) / span * (len(_TICKS) - 1))] for v in vals)

    def plot(self, name, which="train", ax=None):
        """Matplotlib curve when matplotlib is installed."""
        try:
            import matplotlib.pyplot as plt
        except ImportError as e:
            raise ImportError(
                "matplotlib is not installed; use sparkline()/to_csv() on "
                "headless hosts") from e
        vals = self.values(name, which)
        if ax is None:
            _, ax = plt.subplots()
        ax.plot(range(len(vals)), vals, label="%s %s" % (which, name))
        ax.set_xlabel("sample")
        ax.set_ylabel(name)
        ax.legend()
        return ax


class LiveLearningCurve(MetricsLogger):
    """Prints a refreshed sparkline as training proceeds (the reference's
    bokeh live plot, terminal edition)."""

    def __init__(self, metric_name: str = "accuracy", frequent: int = 50):
        super().__init__(frequent=frequent)
        self.metric_name = metric_name

    def train_cb(self, param):
        super().train_cb(param)
        if param.nbatch % self.frequent:  # render at collection cadence
            return
        line = self.sparkline(self.metric_name)
        if line:
            vals = self.values(self.metric_name)
            print("\r%s %s %.4f" % (self.metric_name, line, vals[-1]),
                  end="", flush=True)

"""Notebook helpers (reference python/mxnet/notebook/: live
training-curve plotting). See callback.py."""
from . import callback  # noqa: F401

__all__ = ["callback"]

"""Device context — maps the reference's Context (include/mxnet/base.h:124-196)
onto JAX devices.

Device types: ``cpu``, ``tpu``, and ``gpu`` as an alias of ``tpu`` so reference
training scripts (``--gpus 0,1``) run unchanged.  ``cpu_pinned`` maps to host
memory.  A Context is hashable, usable as a ``with``-scope (current-context
stack, parity with python/mxnet/context.py), and resolves lazily to a concrete
``jax.Device`` so contexts can be constructed before backends initialise.
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise ValueError("unknown device type %s" % device_type)
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    # -- JAX resolution ----------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        ``gpu``/``tpu`` resolve to accelerator devices (whatever platform JAX
        exposes — TPU in production, host CPU devices in tests running under
        ``--xla_force_host_platform_device_count``); ``cpu``/``cpu_pinned``
        prefer the CPU backend when present.
        """
        import jax

        if self.device_type in ("cpu", "cpu_pinned"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.local_devices()
        else:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    # -- with-scope --------------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    def empty_cache(self):
        """Parity no-op: XLA owns the device allocator (reference:
        src/storage/pooled_storage_manager.h ReleaseAll)."""

    @classmethod
    def default_ctx(cls) -> "Context":
        if not hasattr(cls._default_ctx, "value"):
            cls._default_ctx.value = Context("cpu", 0)
        return cls._default_ctx.value


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of the accelerator device so `--gpus` flags keep working on TPU."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


def num_gpus() -> int:
    return num_tpus()


def num_tpus() -> int:
    import jax

    try:
        return len([d for d in jax.local_devices() if d.platform != "cpu"]) or len(
            jax.local_devices()
        )
    except RuntimeError:
        return 0

"""ctypes loader for the native runtime library (src/recordio.cc).

The reference's IO hot path is C++ (src/io/, dmlc-core RecordIO +
ThreadedIter); here the same roles live in libmxtpu.so, loaded via ctypes
(pybind11 is not in this image). The library self-builds with g++ on first
use when missing; every native entry point has a pure-Python fallback, so
the package works without a toolchain (``MXNET_USE_NATIVE=0`` forces the
fallback).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .base import env

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, "libmxtpu.so")
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "src")


def _build():
    srcs = [os.path.join(_SRC_DIR, "recordio.cc"),
            os.path.join(_SRC_DIR, "imgdecode.cc")]
    if not all(os.path.exists(s) for s in srcs):
        return False
    # build to a temp path then rename: concurrent builders and interrupted
    # builds must never leave a half-written .so at the final path
    tmp = "%s.build.%d" % (_SO_PATH, os.getpid())
    try:
        subprocess.check_call(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-pthread",
             "-shared", "-o", tmp] + srcs + ["-ljpeg"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.replace(tmp, _SO_PATH)
        return True
    except (OSError, subprocess.CalledProcessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _bind(lib):
    i64, u8p, u8pp, vp, cp = (ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                              ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                              ctypes.c_void_p, ctypes.c_char_p)
    lib.rio_reader_open.restype = vp
    lib.rio_reader_open.argtypes = [cp]
    lib.rio_read.restype = i64
    lib.rio_read.argtypes = [vp, u8pp, ctypes.POINTER(i64)]
    lib.rio_read_at.restype = i64
    lib.rio_read_at.argtypes = [vp, i64, u8pp]
    lib.rio_reader_reset.argtypes = [vp]
    lib.rio_reader_close.argtypes = [vp]
    lib.rio_writer_open.restype = vp
    lib.rio_writer_open.argtypes = [cp]
    lib.rio_write.restype = i64
    lib.rio_write.argtypes = [vp, u8p, i64]
    lib.rio_writer_close.argtypes = [vp]
    lib.rio_prefetch_open.restype = vp
    lib.rio_prefetch_open.argtypes = [cp, ctypes.c_int]
    lib.rio_prefetch_next.restype = i64
    lib.rio_prefetch_next.argtypes = [vp, u8pp]
    lib.rio_prefetch_close.argtypes = [vp]
    lib.rio_free.argtypes = [u8p]
    lib.rio_abi_version.restype = i64
    ci, szp = ctypes.c_int, ctypes.POINTER(ctypes.c_size_t)
    cip = ctypes.POINTER(ci)
    lib.mxtpu_decode_jpeg_batch_alloc.restype = ci
    lib.mxtpu_decode_jpeg_batch_alloc.argtypes = [u8pp, szp, ci, u8pp, cip,
                                                  cip, ci]
    lib.mxtpu_free_many.argtypes = [u8pp, ci]
    return lib


def _load():
    if not env("MXNET_USE_NATIVE", True, bool):
        return None
    for attempt in range(2):
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = _bind(ctypes.CDLL(_SO_PATH))
            if lib.rio_abi_version() == 2:
                return lib
        except (OSError, AttributeError):
            pass
        # stale/corrupt .so (interrupted build, ABI drift): rebuild once
        try:
            os.unlink(_SO_PATH)
        except OSError:
            return None
    return None


def get_lib():
    """The loaded native library, or None (pure-Python fallback)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if not _TRIED:
            _LIB = _load()
            # publish _TRIED only after _LIB is assigned so the lock-free
            # fast path never observes a half-initialized state
            _TRIED = True
        return _LIB


def have_native() -> bool:
    return get_lib() is not None


def _take(lib, ptr, length) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.rio_free(ptr)


class NativeRecordReader:
    """Sequential/offset reader over libmxtpu (same framing as
    recordio.MXRecordIO)."""

    def __init__(self, path):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.rio_reader_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        if not self._h:
            raise IOError("reader is closed")
        buf = ctypes.POINTER(ctypes.c_uint8)()
        off = ctypes.c_int64()
        n = self._lib.rio_read(self._h, ctypes.byref(buf), ctypes.byref(off))
        if n < 0:
            raise IOError("corrupt RecordIO stream")
        if n == 0 and not buf:
            return None
        return _take(self._lib, buf, n)

    def read_at(self, pos):
        if not self._h:
            raise IOError("reader is closed")
        buf = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.rio_read_at(self._h, pos, ctypes.byref(buf))
        if n < 0:
            raise IOError("corrupt RecordIO stream")
        if n == 0 and not buf:
            return None
        return _take(self._lib, buf, n)

    def reset(self):
        self._lib.rio_reader_reset(self._h)

    def close(self):
        if self._h:
            self._lib.rio_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    def __init__(self, path):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def write(self, buf: bytes) -> int:
        arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        off = self._lib.rio_write(self._h, arr, len(buf))
        if off < 0:
            raise IOError("RecordIO write failed")
        return off

    def close(self):
        if self._h:
            self._lib.rio_writer_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def decode_jpeg_batch(bufs, nthreads=4):
    """Decode a list of JPEG byte strings on a C++ thread pool (GIL-free;
    the reference's OMP decode, iter_image_recordio.cc:140-160).  Header
    parse + allocation + decode all run inside ONE foreign call.

    Returns a list of HWC uint8 RGB numpy arrays; entries that are not
    decodable JPEGs come back as None (caller falls back to PIL).
    """
    import numpy as np

    lib = get_lib()
    if lib is None:
        return [None] * len(bufs)
    n = len(bufs)
    if n == 0:
        return []
    u8p = ctypes.POINTER(ctypes.c_uint8)
    # bytes objects are only read by the C side: cast without copying
    in_ptrs = (u8p * n)(*[ctypes.cast(ctypes.c_char_p(b), u8p) for b in bufs])
    in_lens = (ctypes.c_size_t * n)(*[len(b) for b in bufs])
    out_ptrs = (u8p * n)()
    ws = (ctypes.c_int * n)()
    hs = (ctypes.c_int * n)()
    lib.mxtpu_decode_jpeg_batch_alloc(in_ptrs, in_lens, n, out_ptrs, ws, hs,
                                      nthreads)
    outs = [None] * n
    try:
        for i in range(n):
            if out_ptrs[i]:
                view = np.ctypeslib.as_array(out_ptrs[i],
                                             shape=(hs[i], ws[i], 3))
                outs[i] = view.copy()  # own the memory before C frees it
    finally:
        lib.mxtpu_free_many(out_ptrs, n)
    return outs


class NativePrefetchReader:
    """Background-thread readahead (dmlc::ThreadedIter parity,
    reference src/io/iter_prefetcher.h:28-129)."""

    def __init__(self, path, capacity=16):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.rio_prefetch_open(path.encode(), capacity)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def __iter__(self):
        return self

    def __next__(self):
        if not self._h:
            raise IOError("prefetch reader is closed")
        buf = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.rio_prefetch_next(self._h, ctypes.byref(buf))
        if n < 0:
            raise IOError("corrupt RecordIO stream")
        if n == 0 and not buf:
            raise StopIteration
        return _take(self._lib, buf, n)

    def close(self):
        if self._h:
            self._lib.rio_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

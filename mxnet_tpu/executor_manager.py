"""Legacy multi-device executor manager (reference
python/mxnet/executor_manager.py: `_split_input_slice` workload split +
`DataParallelExecutorManager`, the pre-Module training plumbing that
FeedForward used).

Here the manager is a thin legacy-API adapter over the mesh-native
``module.executor_group.DataParallelExecutorGroup`` — one executor over a
device mesh instead of one executor per device.
"""
from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from .base import MXNetError


def _split_input_slice(batch_size: int, work_load_list: List[float]):
    """Split ``batch_size`` into per-device slices proportional to
    ``work_load_list`` (reference executor_manager.py:15-50)."""
    total = sum(work_load_list)
    if total <= 0:
        raise ValueError("Invalid workload")
    slices = []
    start = 0
    for i, load in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            stop = batch_size
        else:
            stop = min(int(round(start + batch_size * load / total)),
                       batch_size)
        if stop <= start:
            raise ValueError(
                "Too many slices. Some splits are empty (batch %d over %d "
                "workers)" % (batch_size, len(work_load_list)))
        slices.append(slice(start, stop))
        start = stop
    return slices


def _check_arguments(symbol):
    """Reject duplicate argument/aux names (reference
    executor_manager.py:52-80; the bind-time duplicate-var check)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        dup = [n for n in set(arg_names) if arg_names.count(n) > 1]
        raise ValueError(
            "Find duplicated argument name, please make the weight name "
            "non-duplicated, duplicates: %s" % dup)
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise ValueError("Find duplicated auxiliary param name")


def _load_general(data, targets):
    """Copy a list of NDArray/ndarray into a list of target NDArrays."""
    for d_src, d_target in zip(data, targets):
        d_target[:] = d_src


def _load_data(batch, targets):
    _load_general(batch.data, targets)


def _load_label(batch, targets):
    _load_general(batch.label, targets)


class DataParallelExecutorManager:
    """Legacy training-loop helper: bind once over the contexts, then
    ``load_data_batch`` / ``forward`` / ``backward`` / ``update_metric``
    (reference executor_manager.py DataParallelExecutorManager)."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        if sym_gen is not None:
            raise NotImplementedError(
                "sym_gen (per-bucket symbols) is not supported by this "
                "adapter; use mx.mod.BucketingModule for bucketed training")
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, (list, tuple)) else [ctx]
        if work_load_list is None:
            work_load_list = [1] * len(self._ctx)
        if len(work_load_list) != len(self._ctx):
            raise MXNetError("Invalid settings for work load.")
        _check_arguments(symbol)
        self._arg_names = arg_names or symbol.list_arguments()
        self._aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = [d[0] for d in train_data.provide_data]
        label_names = [l[0] for l in (train_data.provide_label or [])]
        self._param_names = param_names or [
            n for n in self._arg_names
            if n not in data_names and n not in label_names]
        from .module.executor_group import DataParallelExecutorGroup

        self._group = DataParallelExecutorGroup(
            symbol, self._ctx, work_load_list,
            train_data.provide_data, train_data.provide_label,
            self._param_names, for_training=True, inputs_need_grad=False)
        self._group.bind_exec(train_data.provide_data,
                              train_data.provide_label)
        self._batch = None
        self.slices = _split_input_slice(
            train_data.batch_size
            if hasattr(train_data, "batch_size")
            else train_data.provide_data[0][1][0], work_load_list)

    # -- params -----------------------------------------------------------
    def set_params(self, arg_params, aux_params):
        self._group.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        self._group.get_params(arg_params, aux_params)

    @property
    def param_names(self):
        return self._param_names

    @property
    def param_arrays(self):
        return self._group.param_arrays

    @property
    def grad_arrays(self):
        return self._group.grad_arrays

    @property
    def aux_arrays(self):
        return self._group.aux_arrays

    # -- the step ---------------------------------------------------------
    def install_monitor(self, monitor):
        self._group.install_monitor(monitor)

    def load_data_batch(self, data_batch):
        self._batch = data_batch

    def forward(self, is_train=False):
        if self._batch is None:
            raise MXNetError("call load_data_batch before forward")
        self._group.forward(self._batch, is_train=is_train)

    def backward(self):
        self._group.backward()

    def update_metric(self, metric, labels):
        self._group.update_metric(metric, labels)

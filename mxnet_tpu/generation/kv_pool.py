"""Paged KV-cache pool — fixed-size pages + per-sequence page tables.

The dense alternative (one ``(max_len, heads, head_dim)`` buffer per
sequence slot) reserves ``max_len x batch`` tokens of HBM whether or not
they are ever written; mixed-length autoregressive traffic wastes most
of it.  Here KV storage is a shared pool of fixed-size pages (the vLLM
PagedAttention layout): a sequence owns ``ceil(len / page_size)`` pages,
listed in order in its page table, so live memory tracks live tokens and
the pool admits as many sequences as actually fit.

Page 0 is reserved as scratch: inactive decode lanes point their
page-table rows at it so their masked-out writes land harmlessly
(ops/paged.py).  Allocation is O(1) off a free list; exhaustion raises
:class:`KVPoolExhaustedError` — the engine's admission backpressure and
preemption signal, never a deadlock.

Watermark accounting (live/peak pages, occupancy) exports through
``mxnet_tpu.telemetry`` gauges; every allocation passes the
``generation.kv.alloc`` fault point so chaos runs can starve the pool
deterministically.
"""
from __future__ import annotations

import threading
from typing import Dict, List

import numpy as np

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["PagedKVPool", "KVPoolExhaustedError"]


class KVPoolExhaustedError(MXNetError):
    """No free pages — backpressure: callers queue, shed, or preempt."""


class PagedKVPool:
    """Host-side paged K/V storage for ``num_layers`` attention layers.

    Parameters
    ----------
    num_pages : int
        Total pool pages INCLUDING the reserved scratch page 0, so
        ``num_pages - 1`` are allocatable.
    page_size : int
        Tokens per page.
    num_layers, num_heads, head_dim : int
        K/V geometry; each layer holds one ``(num_pages, page_size,
        num_heads, head_dim)`` K array and one V array.
    """

    def __init__(self, num_pages, page_size, num_layers, num_heads,
                 head_dim, dtype=np.float32):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self._dtype = np.dtype(dtype)
        shape = (self.num_pages, self.page_size, int(num_heads),
                 int(head_dim))
        self.k_pools = [np.zeros(shape, self._dtype)
                        for _ in range(self.num_layers)]
        self.v_pools = [np.zeros(shape, self._dtype)
                        for _ in range(self.num_layers)]
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lengths: Dict[object, int] = {}
        self.peak_pages = 0
        reg = self._registry = _telemetry.Registry()
        self._g_live = reg.gauge("mxtpu_gen_kv_pages_live")
        self._g_peak = reg.gauge("mxtpu_gen_kv_pages_peak")
        self._g_occ = reg.gauge("mxtpu_gen_kv_pool_occupancy_pct")
        self._c_allocs = reg.counter("mxtpu_gen_kv_page_allocs_total")
        self._c_frees = reg.counter("mxtpu_gen_kv_page_frees_total")
        _telemetry.register_collector(self)

    # -- accounting -------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable pages (scratch page excluded)."""
        return self.num_pages - 1

    def live_pages(self) -> int:
        with self._lock:
            return self.capacity - len(self._free)

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def occupancy(self) -> float:
        return self.live_pages() / float(self.capacity)

    def pages_for(self, num_tokens: int) -> int:
        return -(-int(num_tokens) // self.page_size)  # ceil div

    def seq_length(self, seq_id) -> int:
        with self._lock:
            return self._lengths[seq_id]

    def live_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def _refresh_gauges_locked(self):
        live = self.capacity - len(self._free)
        if live > self.peak_pages:
            self.peak_pages = live
        self._g_live.set(live)
        self._g_peak.set(self.peak_pages)
        self._g_occ.set(int(round(100.0 * live / self.capacity)))

    # -- alloc / extend / free -------------------------------------------
    def can_fit(self, num_tokens: int) -> bool:
        with self._lock:
            return self.pages_for(num_tokens) <= len(self._free)

    def alloc(self, seq_id, num_tokens: int) -> List[int]:
        """Claim pages for a new sequence of ``num_tokens`` tokens;
        returns its page list.  Raises :class:`KVPoolExhaustedError`
        without allocating anything when the pool cannot fit it."""
        faults.fire("generation.kv.alloc")
        need = max(1, self.pages_for(num_tokens))
        with self._lock:
            if seq_id in self._tables:
                raise MXNetError("sequence %r already allocated" % (seq_id,))
            if need > len(self._free):
                raise KVPoolExhaustedError(
                    "KV pool exhausted: need %d pages, %d free (capacity "
                    "%d); retry, shed, or preempt" %
                    (need, len(self._free), self.capacity))
            pages = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = pages
            self._lengths[seq_id] = int(num_tokens)
            self._c_allocs.inc(need)
            self._refresh_gauges_locked()
            return list(pages)

    def extend(self, seq_id, new_length: int) -> List[int]:
        """Grow a sequence to ``new_length`` tokens, claiming new pages
        when it crosses a page boundary.  Raises
        :class:`KVPoolExhaustedError` (state unchanged) when the pool is
        out — the engine preempts a sequence to make room."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            need = self.pages_for(new_length) - len(pages)
            if need > len(self._free):
                raise KVPoolExhaustedError(
                    "KV pool exhausted extending %r: need %d more pages, "
                    "%d free" % (seq_id, need, len(self._free)))
            for _ in range(max(0, need)):
                pages.append(self._free.pop())
            if need > 0:
                self._c_allocs.inc(need)
            self._lengths[seq_id] = int(new_length)
            self._refresh_gauges_locked()
            return list(pages)

    def free(self, seq_id):
        """Return a sequence's pages to the free list (idempotent)."""
        with self._lock:
            pages = self._tables.pop(seq_id, None)
            self._lengths.pop(seq_id, None)
            if pages:
                self._free.extend(reversed(pages))
                self._c_frees.inc(len(pages))
                self._refresh_gauges_locked()

    # -- page-table / data plumbing for the decode step ------------------
    def page_table_row(self, seq_id, max_pages: int) -> np.ndarray:
        """The sequence's page list padded to ``max_pages`` with the
        scratch page 0 (the decode step's per-lane page-table row)."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            if len(pages) > max_pages:
                raise MXNetError(
                    "sequence %r spans %d pages > max_pages %d"
                    % (seq_id, len(pages), max_pages))
            row = np.zeros((max_pages,), np.float32)
            row[:len(pages)] = pages
            return row

    def write_prefill(self, seq_id, layer, k, v, length: int):
        """Scatter a prefill pass's K/V (``(seq_len, heads, head_dim)``,
        only the first ``length`` rows real) into the sequence's pages."""
        with self._lock:
            pages = self._tables[seq_id]
        ps = self.page_size
        kp, vp = self.k_pools[layer], self.v_pools[layer]
        for start in range(0, int(length), ps):
            page = pages[start // ps]
            n = min(ps, int(length) - start)
            kp[page, :n] = k[start:start + n]
            vp[page, :n] = v[start:start + n]

    def snapshot(self) -> dict:
        with self._lock:
            live = self.capacity - len(self._free)
            return {"capacity": self.capacity, "live_pages": live,
                    "peak_pages": self.peak_pages,
                    "sequences": len(self._tables),
                    "occupancy": live / float(self.capacity)}

    def render_prometheus(self):
        """Collector hook for ``telemetry.render_prometheus()``."""
        return self._registry.render_prometheus()

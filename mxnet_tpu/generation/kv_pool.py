"""Paged KV-cache pool — fixed-size pages, per-sequence page tables,
refcounted copy-on-write sharing, and a cross-request prefix cache.

The dense alternative (one ``(max_len, heads, head_dim)`` buffer per
sequence slot) reserves ``max_len x batch`` tokens of HBM whether or not
they are ever written; mixed-length autoregressive traffic wastes most
of it.  Here KV storage is a shared pool of fixed-size pages (the vLLM
PagedAttention layout): a sequence owns ``ceil(len / page_size)`` pages,
listed in order in its page table, so live memory tracks live tokens and
the pool admits as many sequences as actually fit.

Page 0 is reserved as scratch: inactive decode lanes point their
page-table rows at it so their masked-out writes land harmlessly
(ops/paged.py).  Allocation is O(1) off a free list; exhaustion raises
:class:`KVPoolExhaustedError` — the engine's admission backpressure and
preemption signal, never a deadlock.

Prefix caching (cross-request): every COMPLETE page a sequence fills is
content-addressed by a page-granular rolling hash over the token ids it
holds (each page's digest chains over every preceding token, so two
sequences share page ``j`` only when their first ``(j+1)*page_size``
tokens are identical).  Pages carry refcounts: :meth:`alloc_prefix`
resolves the longest indexed prefix of a new prompt and takes references
on the hit pages instead of recomputing them; :meth:`free` decrements,
and a page whose refcount reaches 0 while still indexed is RETAINED as
reusable cache rather than returned to the free list — a bounded LRU
(``MXNET_GEN_PREFIX_CACHE_PAGES``) that evicts only refcount-0 pages,
either on demand (allocation pressure) or to stay under the bound.  A
lane about to write into a shared page copies it first
(:meth:`ensure_writable` — copy-on-write), so a diverging stream can
never mutate history another stream (or the cache) still reads.

Watermark accounting (live/peak pages, occupancy over the allocatable
``num_pages - 1``, shared/cached page counts) exports through
``mxnet_tpu.telemetry`` gauges; every allocation passes the
``generation.kv.alloc`` fault point and every prefix lookup passes
``generation.prefix.lookup`` so chaos runs can starve or blind the pool
deterministically (a failed lookup degrades to a cache miss, never a
failed stream).
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["PagedKVPool", "KVPoolExhaustedError"]


class KVPoolExhaustedError(MXNetError):
    """No free pages — backpressure: callers queue, shed, or preempt."""


def _page_digest(prev: bytes, chunk) -> bytes:
    """Rolling content hash for one page worth of token ids: chains the
    previous page's digest so a digest identifies the ENTIRE prefix up
    to and including this page, not just its own tokens."""
    h = hashlib.sha1(prev)
    h.update(np.asarray(chunk, np.int64).tobytes())
    return h.digest()


class PagedKVPool:
    """Host-side paged K/V storage for ``num_layers`` attention layers.

    Parameters
    ----------
    num_pages : int
        Total pool pages INCLUDING the reserved scratch page 0, so
        ``num_pages - 1`` are allocatable.
    page_size : int
        Tokens per page.
    num_layers, num_heads, head_dim : int
        K/V geometry; each layer holds one ``(num_pages, page_size,
        num_heads, head_dim)`` K array and one V array.
    prefix_cache_pages : int, optional
        Upper bound on refcount-0 pages the prefix index retains after
        their last owner frees them (0, the default, disables prefix
        caching entirely — legacy alloc/free semantics).
    """

    def __init__(self, num_pages, page_size, num_layers, num_heads,
                 head_dim, dtype=np.float32, prefix_cache_pages: int = 0):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.prefix_cache_pages = max(0, int(prefix_cache_pages))
        self._dtype = np.dtype(dtype)
        shape = (self.num_pages, self.page_size, int(num_heads),
                 int(head_dim))
        self.k_pools = [np.zeros(shape, self._dtype)
                        for _ in range(self.num_layers)]
        self.v_pools = [np.zeros(shape, self._dtype)
                        for _ in range(self.num_layers)]
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lengths: Dict[object, int] = {}
        # -- sharing / prefix-cache state ---------------------------------
        self._ref: Dict[int, int] = {}          # page -> refcount (live)
        self._index: "OrderedDict[bytes, int]" = OrderedDict()  # LRU->MRU
        self._page_key: Dict[int, bytes] = {}   # indexed page -> digest
        self._cached = 0                        # indexed pages at ref 0
        self._chain: Dict[object, Tuple[int, bytes]] = {}  # seq -> (pages
        #                                     registered, digest so far)
        self.peak_pages = 0
        reg = self._registry = _telemetry.Registry()
        self._g_live = reg.gauge("mxtpu_gen_kv_pages_live")
        self._g_peak = reg.gauge("mxtpu_gen_kv_pages_peak")
        self._g_occ = reg.gauge("mxtpu_gen_kv_pool_occupancy_pct")
        # ratio gauge over the ALLOCATABLE pages (num_pages - 1): hits
        # exactly 1.0 at a full pool, unlike pre-fix math that could
        # never reach it when derived from the raw num_pages
        self._g_occ_ratio = reg.gauge("mxtpu_gen_kv_occupancy")
        self._g_shared = reg.gauge("mxtpu_gen_pages_shared")
        self._g_cached = reg.gauge("mxtpu_gen_prefix_cached_pages")
        self._c_allocs = reg.counter("mxtpu_gen_kv_page_allocs_total")
        self._c_frees = reg.counter("mxtpu_gen_kv_page_frees_total")
        self._c_hits = reg.counter("mxtpu_gen_prefix_hits_total")
        self._c_misses = reg.counter("mxtpu_gen_prefix_misses_total")
        self._c_evict = reg.counter("mxtpu_gen_prefix_evictions_total")
        self._c_cow = reg.counter("mxtpu_gen_kv_cow_copies_total")
        self._c_hit_tokens = reg.counter("mxtpu_gen_prefix_hit_tokens_total")
        _telemetry.register_collector(self)

    # -- accounting -------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable pages (scratch page excluded)."""
        return self.num_pages - 1

    def live_pages(self) -> int:
        with self._lock:
            return self._live_locked()

    def _live_locked(self) -> int:
        """Pages owned by at least one live sequence — excludes scratch
        page 0, the free list, AND retained (refcount-0) cache pages."""
        return self.capacity - len(self._free) - self._cached

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def reclaimable_pages(self) -> int:
        """Pages an allocation can obtain: the free list plus retained
        refcount-0 cache pages (evicted on demand)."""
        with self._lock:
            return len(self._free) + self._cached

    def cached_pages(self) -> int:
        with self._lock:
            return self._cached

    def shared_pages(self) -> int:
        """Pages referenced by more than one live sequence."""
        with self._lock:
            return sum(1 for r in self._ref.values() if r > 1)

    def total_refcount(self) -> int:
        """Sum of live refcounts — 0 after every sequence closed means
        no leaked shared pages (the chaos-run invariant)."""
        with self._lock:
            return sum(self._ref.values())

    def occupancy(self) -> float:
        return self.live_pages() / float(self.capacity)

    def pages_for(self, num_tokens: int) -> int:
        return -(-int(num_tokens) // self.page_size)  # ceil div

    def seq_length(self, seq_id) -> int:
        with self._lock:
            return self._lengths[seq_id]

    def live_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def _refresh_gauges_locked(self):
        live = self._live_locked()
        if live > self.peak_pages:
            self.peak_pages = live
        self._g_live.set(live)
        self._g_peak.set(self.peak_pages)
        self._g_occ.set(int(round(100.0 * live / self.capacity)))
        self._g_occ_ratio.set(round(live / float(self.capacity), 4))
        self._g_shared.set(sum(1 for r in self._ref.values() if r > 1))
        self._g_cached.set(self._cached)

    # -- prefix-index internals (all called with the lock held) ----------
    def _evict_one_locked(self) -> bool:
        """Drop the least-recently-used refcount-0 indexed page back to
        the free list.  Returns False when nothing is evictable."""
        for key, page in self._index.items():
            if self._ref.get(page, 0) == 0:
                del self._index[key]
                del self._page_key[page]
                self._cached -= 1
                self._free.append(page)
                self._c_evict.inc()
                return True
        return False

    def _reserve_locked(self, need: int):
        """Ensure ``need`` pages are on the free list, evicting retained
        cache pages LRU-first; raises when the pool genuinely cannot."""
        while len(self._free) < need:
            if not self._evict_one_locked():
                raise KVPoolExhaustedError(
                    "KV pool exhausted: need %d pages, %d free (capacity "
                    "%d); retry, shed, or preempt" %
                    (need, len(self._free), self.capacity))

    def _enforce_cache_bound_locked(self):
        while self._cached > self.prefix_cache_pages:
            if not self._evict_one_locked():
                break

    def _release_page_locked(self, page: int):
        """Drop one reference; a refcount-0 page is retained when still
        indexed (and retention is enabled), else returned to the free
        list."""
        r = self._ref.get(page, 0) - 1
        if r > 0:
            self._ref[page] = r
            return
        self._ref.pop(page, None)
        key = self._page_key.get(page)
        if key is not None and self.prefix_cache_pages > 0:
            self._cached += 1
        else:
            if key is not None:
                del self._index[key]
                del self._page_key[page]
            self._free.append(page)

    def _match_prefix_locked(self, tokens) -> Tuple[List[int], List[bytes]]:
        """Longest run of indexed pages covering ``tokens``' complete
        page chunks; returns (pages, their chained digests)."""
        ps = self.page_size
        pages: List[int] = []
        digests: List[bytes] = []
        key = b""
        for start in range(0, (len(tokens) // ps) * ps, ps):
            key = _page_digest(key, tokens[start:start + ps])
            page = self._index.get(key)
            if page is None:
                break
            pages.append(page)
            digests.append(key)
        return pages, digests

    # -- alloc / extend / free -------------------------------------------
    def can_fit(self, num_tokens: int) -> bool:
        with self._lock:
            return (self.pages_for(num_tokens)
                    <= len(self._free) + self._cached)

    def alloc(self, seq_id, num_tokens: int) -> List[int]:
        """Claim pages for a new sequence of ``num_tokens`` tokens;
        returns its page list.  Raises :class:`KVPoolExhaustedError`
        without allocating anything when the pool cannot fit it."""
        pages, _ = self.alloc_prefix(seq_id, num_tokens, tokens=None)
        return pages

    def alloc_prefix(self, seq_id, num_tokens: int,
                     tokens=None) -> Tuple[List[int], int]:
        """Claim pages for a new sequence, resolving ``tokens`` (the
        prompt) against the prefix index first.  Returns ``(pages,
        cached_tokens)`` where the first ``cached_tokens`` positions'
        K/V are already materialized in shared pages — the caller skips
        prefill for them and feeds only the remainder.

        The hit policy is conservative: a match is only taken when the
        cached run covers at least as many tokens as the leftover
        suffix, so a near-miss never trades one big prefill for a long
        dribble of per-token catch-up steps.  ``cached_tokens`` is
        capped at ``num_tokens - 1`` — the final prompt position must
        always be (re)fed so its logits exist to produce the first
        generated token; when the cache covers it too, the write lands
        in a shared page and copy-on-write splits it.

        A fault injected at ``generation.prefix.lookup`` degrades the
        lookup to a miss (full prefill) instead of failing the stream.
        """
        faults.fire("generation.kv.alloc")
        lookup_ok = True
        if tokens is not None and self.prefix_cache_pages > 0:
            try:
                faults.fire("generation.prefix.lookup")
            except Exception:
                lookup_ok = False
        need_total = max(1, self.pages_for(num_tokens))
        with self._lock:
            if seq_id in self._tables:
                raise MXNetError("sequence %r already allocated" % (seq_id,))
            taken: List[int] = []
            digests: List[bytes] = []
            cached_tokens = 0
            if tokens is not None and self.prefix_cache_pages > 0 \
                    and lookup_ok:
                hit_pages, hit_digests = self._match_prefix_locked(tokens)
                usable = min(len(hit_pages) * self.page_size,
                             int(num_tokens) - 1)
                if usable >= 1 and (int(num_tokens) - usable) <= usable:
                    cached_tokens = usable
                    n_pages = self.pages_for(usable)
                    taken = hit_pages[:n_pages]
                    digests = hit_digests[:n_pages]
            if tokens is not None and self.prefix_cache_pages > 0:
                if cached_tokens:
                    self._c_hits.inc()
                    self._c_hit_tokens.inc(cached_tokens)
                else:
                    self._c_misses.inc()
            fresh_need = need_total - len(taken)
            self._reserve_locked(fresh_need)
            for page, key in zip(taken, digests):
                r = self._ref.get(page, 0)
                if r == 0:
                    self._cached -= 1
                self._ref[page] = r + 1
                self._index.move_to_end(key)
            fresh = [self._free.pop() for _ in range(fresh_need)]
            for page in fresh:
                self._ref[page] = 1
            pages = taken + fresh
            self._tables[seq_id] = pages
            self._lengths[seq_id] = int(num_tokens)
            self._chain[seq_id] = (len(taken),
                                   digests[-1] if digests else b"")
            self._c_allocs.inc(fresh_need)
            self._refresh_gauges_locked()
            return list(pages), cached_tokens

    def extend(self, seq_id, new_length: int) -> List[int]:
        """Grow a sequence to ``new_length`` tokens, claiming new pages
        when it crosses a page boundary.  Raises
        :class:`KVPoolExhaustedError` (state unchanged) when the pool is
        out — the engine preempts a sequence to make room."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            need = self.pages_for(new_length) - len(pages)
            if need > 0:
                self._reserve_locked(need)
            for _ in range(max(0, need)):
                page = self._free.pop()
                self._ref[page] = 1
                pages.append(page)
            if need > 0:
                self._c_allocs.inc(need)
            self._lengths[seq_id] = max(self._lengths[seq_id],
                                        int(new_length))
            self._refresh_gauges_locked()
            return list(pages)

    def free(self, seq_id):
        """Release a sequence's references (idempotent).  Unshared pages
        return to the free list; pages other sequences still reference
        merely decrement; refcount-0 pages the prefix index still names
        are retained as cache, LRU-bounded by ``prefix_cache_pages``."""
        with self._lock:
            pages = self._tables.pop(seq_id, None)
            self._lengths.pop(seq_id, None)
            self._chain.pop(seq_id, None)
            if pages:
                # reversed keeps the legacy free-list LIFO order: a
                # follow-up alloc reuses the pages lowest-id-first
                for page in reversed(pages):
                    self._release_page_locked(page)
                self._c_frees.inc(len(pages))
                self._enforce_cache_bound_locked()
                self._refresh_gauges_locked()

    # -- copy-on-write ----------------------------------------------------
    def is_shared(self, seq_id, position: int) -> bool:
        """True when the page holding ``position`` must not be written
        by this sequence (another reference or the index still reads
        it)."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            idx = int(position) // self.page_size
            if idx >= len(pages):
                return False
            page = pages[idx]
            return self._ref.get(page, 0) > 1 or page in self._page_key

    def ensure_writable(self, seq_id, position: int) -> bool:
        """Copy-on-write: when the page holding ``position`` is shared
        (refcount > 1) or still prefix-indexed, copy its K/V into a
        fresh private page and repoint this sequence's table entry, so
        the upcoming write can never mutate data another stream or the
        cache reads.  Returns True when a copy happened.  Raises
        :class:`KVPoolExhaustedError` when no page can be claimed."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            idx = int(position) // self.page_size
            if idx >= len(pages):
                return False  # beyond allocation: write hits scratch
            page = pages[idx]
            if self._ref.get(page, 0) <= 1 and page not in self._page_key:
                return False
            self._reserve_locked(1)
            fresh = self._free.pop()
            self._ref[fresh] = 1
            for layer in range(self.num_layers):
                self.k_pools[layer][fresh] = self.k_pools[layer][page]
                self.v_pools[layer][fresh] = self.v_pools[layer][page]
            pages[idx] = fresh
            self._release_page_locked(page)
            # the chain state survives a COW: digests are content-based
            # (over token ids), and the index keeps naming the ORIGINAL
            # page, whose bytes this sequence can no longer touch
            self._c_cow.inc()
            self._c_allocs.inc()
            self._enforce_cache_bound_locked()
            self._refresh_gauges_locked()
            return True

    # -- prefix registration ----------------------------------------------
    def register_prefix(self, seq_id, tokens) -> int:
        """Publish this sequence's newly COMPLETE pages (every position
        written and final) into the prefix index under their rolling
        content digests.  ``tokens`` must cover exactly the positions
        whose K/V is materialized and valid.  Incremental and
        idempotent; returns the number of pages newly indexed."""
        if self.prefix_cache_pages <= 0:
            return 0
        ps = self.page_size
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                return 0
            n_reg, key = self._chain.get(seq_id, (0, b""))
            complete = min(len(tokens) // ps, len(pages))
            added = 0
            for j in range(n_reg, complete):
                key = _page_digest(key, tokens[j * ps:(j + 1) * ps])
                page = pages[j]
                if key not in self._index and page not in self._page_key:
                    self._index[key] = page
                    self._page_key[page] = key
                    added += 1
            self._chain[seq_id] = (complete, key)
            if added:
                self._refresh_gauges_locked()
            return added

    # -- page-table / data plumbing for the decode step ------------------
    def page_table_row(self, seq_id, max_pages: int) -> np.ndarray:
        """The sequence's page list padded to ``max_pages`` with the
        scratch page 0 (the decode step's per-lane page-table row)."""
        with self._lock:
            pages = self._tables.get(seq_id)
            if pages is None:
                raise MXNetError("unknown sequence %r" % (seq_id,))
            if len(pages) > max_pages:
                raise MXNetError(
                    "sequence %r spans %d pages > max_pages %d"
                    % (seq_id, len(pages), max_pages))
            row = np.zeros((max_pages,), np.float32)
            row[:len(pages)] = pages
            return row

    def write_prefill(self, seq_id, layer, k, v, length: int):
        """Scatter a prefill pass's K/V (``(seq_len, heads, head_dim)``,
        only the first ``length`` rows real) into the sequence's pages."""
        with self._lock:
            pages = self._tables[seq_id]
        ps = self.page_size
        kp, vp = self.k_pools[layer], self.v_pools[layer]
        for start in range(0, int(length), ps):
            page = pages[start // ps]
            n = min(ps, int(length) - start)
            kp[page, :n] = k[start:start + n]
            vp[page, :n] = v[start:start + n]

    def snapshot(self) -> dict:
        with self._lock:
            live = self._live_locked()
            return {"capacity": self.capacity, "live_pages": live,
                    "peak_pages": self.peak_pages,
                    "sequences": len(self._tables),
                    "occupancy": live / float(self.capacity),
                    "shared_pages": sum(1 for r in self._ref.values()
                                        if r > 1),
                    "cached_pages": self._cached,
                    "prefix_index_size": len(self._index),
                    "prefix_hits": self._c_hits.value,
                    "prefix_misses": self._c_misses.value,
                    "prefix_evictions": self._c_evict.value,
                    "cow_copies": self._c_cow.value,
                    "total_refcount": sum(self._ref.values())}

    def render_prometheus(self):
        """Collector hook for ``telemetry.render_prometheus()``."""
        return self._registry.render_prometheus()

"""Generative serving — continuous batching + paged KV-cache.

``kv_pool``: fixed-size KV pages + per-sequence page tables, so KV
memory scales with live tokens instead of max_len x batch.
``engine``: :class:`DecodeEngine`, iteration-level continuous batching
over fixed-shape per-lane-bucket decode executables (admit/retire every
step, zero post-warmup recompiles, streaming :class:`GenStream`
handles).  Token-path optimizations: cross-request prefix caching
(content-hashed copy-on-write KV pages, ``MXNET_GEN_PREFIX_CACHE_PAGES``)
and speculative decoding (draft model + fused verify pass, bit-identical
greedy acceptance, autotuned draft length).  Serving integration
(``generate`` SLO class, ``POST /generate`` token streaming) lives in
``mxnet_tpu.serving``.
"""
from .engine import DecodeEngine, GenStream
from .kv_pool import KVPoolExhaustedError, PagedKVPool

__all__ = ["DecodeEngine", "GenStream", "PagedKVPool",
           "KVPoolExhaustedError"]

"""DecodeEngine — iteration-level continuous batching over paged KV.

Autoregressive serving has two phases with opposite shapes: *prefill*
(one big parallel pass over the prompt) and *decode* (one token per
sequence per step, forever).  Request-level batching couples both to
the slowest member of a batch; iteration-level ("continuous") batching
instead re-forms the batch EVERY decode step — new sequences are
admitted into free lanes the moment prefill finishes, finished ones
retire immediately — so short requests never wait for long ones and
the decode executable stays saturated (Orca / vLLM, PAPERS.md).

Two token-path optimizations ride on top of the paged pool:

* **Cross-request prefix caching** (``prefix_cache_pages`` /
  ``MXNET_GEN_PREFIX_CACHE_PAGES``): admission resolves the prompt
  against :class:`~.kv_pool.PagedKVPool`'s content-hash prefix index.
  A fully-cached prompt skips prefill entirely — the sequence enters
  decode with ``next_pos`` pointing at its LAST prompt position, so
  TTFT collapses to ONE engine iteration.  Pages are refcounted and
  copy-on-write: before any write into a potentially shared page the
  engine calls ``ensure_writable``.  Every complete page a sequence
  materializes is re-published (``register_prefix``), which also makes
  preemption cheap: the re-admitted sequence finds its own pages in
  the index instead of re-prefilling prompt+generated from scratch.

* **Speculative decoding** (``draft=``): a small draft model proposes
  K tokens per iteration (its own paged pool + decode executables),
  then ONE windowed target pass — the same teacher-forcing graph as
  prefix catch-up (``models.transformer.get_transformer_lm_catchup``),
  since every feed token is known before the call — scores all K+1
  slots in a single causal forward.  Greedy acceptance keeps every
  token whose draft matched the target argmax, so transcripts match
  non-speculative greedy (asserted per-K by the spec-parity tests).  A
  per-stream acceptance-rate EWMA feeds the ``draft_k`` autotune site
  (objective: accepted tokens per target FLOP), and the winning K is
  resolved at construction so it travels inside ``spec()`` / AOT
  bundles — a restored replica speculates with zero re-tuning.

XLA discipline: every XLA-visible shape here is static.

* Prefill runs through one :class:`~mxnet_tpu.serving.batcher.
  BucketedPredictor` per prompt-length bucket (pow2 lengths), i.e. the
  same shape-quantized executables the scoring tier uses.
* Decode is a fixed-lane slotted program (``models.transformer.
  get_transformer_lm_decode``): ``lanes`` sequences advance one token
  through per-lane page tables into a shared paged KV pool
  (:mod:`.kv_pool`), compiled ONCE per lane-count bucket and primed
  through the PR 10 compile cache (entry kinds ``gen-step`` /
  ``gen-prefill`` / ``gen-verify`` / ``gen-draft-step`` /
  ``gen-draft-prefill``), so AOT bundles restore a generate-ready
  replica with zero cold compiles.

Backpressure: admission is a bounded pending queue (reject =
:class:`~mxnet_tpu.serving.batcher.QueueFullError`, the HTTP 429/503
contract) plus KV-pool capacity; a mid-decode pool exhaustion preempts
the youngest lane (its pages are freed — though complete ones stay in
the prefix index — and the sequence re-queues for re-admission of
prompt+generated; greedy decode is deterministic, so the stream
continues seamlessly), which bounds memory without ever deadlocking.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from ..serving.batcher import (BucketedPredictor, DeadlineExceededError,
                               QueueFullError, ServerClosedError,
                               pow2_buckets)
from .kv_pool import KVPoolExhaustedError, PagedKVPool

__all__ = ["DecodeEngine", "GenStream"]


def _autotune_engine_config(num_layers, num_heads, head_dim, max_seq_len,
                            dtype, max_lanes):
    """Tuned {lane_buckets, page_size} for this model geometry, or None.

    The objective is analytic and deterministic — no lowering: expected
    padded-lane waste under uniform live-lane demand, KV fragmentation
    of a half page per sequence, a per-bucket compile-cost term (every
    lane bucket is one more decode executable to build and keep warm)
    and a page-table-length term penalizing tiny pages."""
    try:
        from .. import autotune
    except Exception:
        return None
    if not autotune.enabled():
        return None
    key = {"num_layers": int(num_layers), "num_heads": int(num_heads),
           "head_dim": int(head_dim), "max_seq_len": int(max_seq_len),
           "max_lanes": int(max_lanes), "dtype": str(np.dtype(dtype))}

    def score(cand):
        buckets = sorted(int(b) for b in cand["lane_buckets"])
        page = int(cand["page_size"])
        waste = 0.0
        for n in range(1, max_lanes + 1):
            b = next((b for b in buckets if b >= n), buckets[-1])
            waste += (b - n) / float(b)
        waste /= max_lanes
        frag = (page - 1) / 2.0 / max(1.0, max_seq_len / 2.0)
        return (waste + frag + 0.02 * len(buckets)
                + 0.0005 * (max_seq_len / float(page)))

    return autotune.get_or_tune(
        "decode_engine", key,
        candidates=autotune.spaces.decode_engine(max_lanes, max_seq_len),
        score_fn=score, default=None)


def _autotune_draft_k(num_layers, hidden, draft_layers, draft_hidden,
                      acceptance):
    """Tuned {k: draft length} for a (target, draft) geometry pair, or
    None.  Analytic objective, lower is better: expected cost per
    accepted token.  One iteration costs ``(k+1)`` target-token-FLOPs
    for the fused verify pass plus ``rho*k`` for the draft rounds
    (``rho`` = draft/target per-token FLOP ratio, dominated by
    ``layers*hidden^2``), and yields ``sum(a^i, i=0..k)`` expected
    tokens under per-token acceptance probability ``a`` — the standard
    speculative-decoding geometric progress model."""
    try:
        from .. import autotune
    except Exception:
        return None
    if not autotune.enabled():
        return None
    acceptance = min(0.99, max(0.0, float(acceptance)))
    key = {"num_layers": int(num_layers), "hidden": int(hidden),
           "draft_layers": int(draft_layers),
           "draft_hidden": int(draft_hidden),
           "acceptance": round(acceptance, 1)}
    rho = ((int(draft_layers) * float(draft_hidden) ** 2)
           / (int(num_layers) * float(hidden) ** 2))

    def score(cand):
        k = int(cand["k"])
        expected = sum(acceptance ** i for i in range(k + 1))
        return ((k + 1) + rho * k) / expected

    return autotune.get_or_tune(
        "draft_k", key, candidates=autotune.spaces.draft_k(),
        score_fn=score, default=None)


register_env("MXNET_GEN_PAGE_SIZE", 16, int,
             "KV-pool page size (tokens per page) for DecodeEngine.")
register_env("MXNET_GEN_NUM_PAGES", 128, int,
             "KV-pool page count (page 0 is reserved scratch) for "
             "DecodeEngine.")
register_env("MXNET_GEN_MAX_LANES", 8, int,
             "Largest decode lane-count bucket (max sequences advancing "
             "per decode step).")
register_env("MXNET_GEN_MAX_NEW_TOKENS", 64, int,
             "Default generation budget when a request does not say.")
register_env("MXNET_GEN_PENDING_QUEUE", 256, int,
             "Bounded admission queue for DecodeEngine.submit; beyond it "
             "submissions raise QueueFullError (HTTP 429).")
register_env("MXNET_GEN_PREFIX_CACHE_PAGES", 0, int,
             "Max refcount-0 KV pages the cross-request prefix index may "
             "retain (LRU-evicted); 0 disables prefix caching.")
register_env("MXNET_GEN_DRAFT_K", 4, int,
             "Speculative draft length (tokens proposed per iteration) "
             "when a draft model is configured and no tuned/explicit K "
             "is available.")

_DONE = object()  # GenStream queue sentinel


class GenStream:
    """One request's streaming handle: iterate tokens as they decode.

    ``for tok in stream`` yields generated token ids incrementally;
    :meth:`result` blocks for the full list.  ``ttft_ms`` / ``itl_ms``
    expose this request's observed first-token latency and inter-token
    gaps once available.  Token-path introspection: ``prefill_tokens``
    (prompt positions actually prefilled, across re-admissions),
    ``cached_prefix_tokens`` (positions served from the prefix cache),
    ``ttft_iters`` (engine iterations before the first token — 0 when
    prefill itself emitted it, 1 for a fully-cached prompt),
    ``draft_proposed`` / ``draft_accepted`` / ``accept_rate`` (per-
    stream speculative acceptance EWMA)."""

    def __init__(self, prompt, max_new_tokens):
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.ttft_ms: Optional[float] = None
        self.itl_ms: List[float] = []
        self.prefill_tokens = 0
        self.cached_prefix_tokens = 0
        self.ttft_iters: Optional[int] = None
        self.draft_proposed = 0
        self.draft_accepted = 0
        self.accept_rate: Optional[float] = None
        self._t0 = time.monotonic()
        self._t_last = None
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None

    # -- engine side ------------------------------------------------------
    def _emit(self, token: int) -> float:
        """Record one generated token; returns the gap (ms) it observed
        (TTFT for the first token, ITL after)."""
        now = time.monotonic()
        if self._t_last is None:
            gap = (now - self._t0) * 1e3
            self.ttft_ms = gap
        else:
            gap = (now - self._t_last) * 1e3
            self.itl_ms.append(gap)
        self._t_last = now
        self.tokens.append(int(token))
        self._q.put(int(token))
        return gap

    def _finish(self, exc: Optional[BaseException] = None):
        if self._done.is_set():
            return
        self._exc = exc
        self._done.set()
        self._q.put(_DONE)

    # -- consumer side ----------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _DONE:
                if self._exc is not None:
                    raise self._exc
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation still running")
        if self._exc is not None:
            raise self._exc
        return list(self.tokens)


class _Seq:
    """Engine-internal live-sequence state (one decode lane's occupant).

    ``next_pos`` is the feed cursor: the position whose token goes into
    the NEXT decode/verify slot (every position below it has final K/V
    materialized in the pool).  Steady state keeps ``next_pos ==
    len(tokens) - 1``; a cached-prefix admission starts it at the hit
    length, a partial hit or a re-admitted preemptee walks the known
    suffix forward one slot per step without emitting.  ``draft_pos``
    is the same cursor for the draft model's pool; ``limit`` is
    ``len(prompt) + max_new`` — no position at or beyond it is ever
    fed, so pool allocations never outgrow the admission-time check."""

    __slots__ = ("sid", "stream", "tokens", "gen_count", "max_new",
                 "deadline", "eos_id", "admitted_at", "next_pos",
                 "draft_pos", "iters", "limit")

    def __init__(self, sid, stream, deadline, eos_id):
        self.sid = sid
        self.stream = stream
        self.tokens = list(stream.prompt)  # prompt + generated so far
        self.gen_count = len(stream.tokens)
        self.max_new = stream.max_new_tokens
        self.deadline = deadline  # absolute monotonic seconds or None
        self.eos_id = eos_id
        self.admitted_at = 0.0
        self.next_pos = 0
        self.draft_pos = 0
        self.iters = 0
        self.limit = len(stream.prompt) + self.max_new


class _GenMetrics:
    """Telemetry collector for one engine: token throughput, TTFT/ITL
    histograms, admission/retire/preempt counters, lane occupancy, and
    the speculative-decoding draft economy."""

    def __init__(self):
        reg = self._registry = _telemetry.Registry()
        self.tokens = reg.counter("mxtpu_gen_tokens_total")
        self.admitted = reg.counter("mxtpu_gen_sequences_admitted_total")
        self.retired = reg.counter("mxtpu_gen_sequences_retired_total")
        self.preempted = reg.counter("mxtpu_gen_sequences_preempted_total")
        self.expired = reg.counter("mxtpu_gen_sequences_expired_total")
        self.rejected = reg.counter("mxtpu_gen_sequences_rejected_total")
        self.failed = reg.counter("mxtpu_gen_sequences_failed_total")
        self.steps = reg.counter("mxtpu_gen_decode_steps_total")
        self.cold_steps = reg.counter("mxtpu_gen_decode_cold_steps_total")
        self.cached_admissions = reg.counter(
            "mxtpu_gen_prefix_cached_admissions_total")
        self.draft_proposed = reg.counter("mxtpu_gen_draft_proposed_total")
        self.draft_accepted = reg.counter("mxtpu_gen_draft_accepted_total")
        self.spec_fallbacks = reg.counter("mxtpu_gen_spec_fallbacks_total")
        # 0.5ms .. ~16s exponential buckets
        self.ttft = reg.histogram("mxtpu_gen_ttft_ms")
        self.itl = reg.histogram("mxtpu_gen_itl_ms")
        self.g_active = reg.gauge("mxtpu_gen_active_lanes")
        self.g_pending = reg.gauge("mxtpu_gen_pending_requests")
        self.g_accept = reg.gauge("mxtpu_gen_draft_accept_rate")
        _telemetry.register_collector(self)

    def render_prometheus(self):
        return self._registry.render_prometheus()


class DecodeEngine:
    """Continuous-batching generation over a decoder-only LM checkpoint.

    Parameters
    ----------
    params : dict | str
        ``{name: array}`` (``arg:`` prefixes allowed) or a ``.params``
        path — the ``get_transformer_lm`` training checkpoint; all
        prefill/decode executors share one copy of the weights.
    vocab_size, num_layers, num_heads, hidden, max_seq_len
        Model geometry (must match the checkpoint).
    lane_buckets : sequence of int, optional
        Decode lane-count buckets (default ``pow2_buckets(
        MXNET_GEN_MAX_LANES)``); one executable per bucket.
    page_size, num_pages : int, optional
        KV-pool geometry (``MXNET_GEN_PAGE_SIZE`` / ``_NUM_PAGES``).
    prefill_len_buckets, prefill_batch_buckets
        Prompt-length and prefill-batch shape quantization; one
        :class:`BucketedPredictor` per length bucket.
    eos_id : int, optional
        Token id that ends a sequence early.
    prefix_cache_pages : int, optional
        Cross-request prefix-cache retention bound (refcount-0 pages
        the index may keep); default ``MXNET_GEN_PREFIX_CACHE_PAGES``,
        0 disables caching entirely (legacy semantics).
    draft : dict, optional
        Speculative-decoding draft model: ``{"params": path-or-dict,
        "num_layers": int, "num_heads": int, "hidden": int,
        "k": int or None, "acceptance_hint": float}``.  ``k`` None
        consults the ``draft_k`` autotune site, then
        ``MXNET_GEN_DRAFT_K``; the RESOLVED value is stored back into
        :meth:`spec` so bundles/replicas rebuild without re-tuning.
    """

    def __init__(self, params, vocab_size, num_layers=4, num_heads=8,
                 hidden=512, max_seq_len=128,
                 lane_buckets: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_len_buckets: Optional[Sequence[int]] = None,
                 prefill_batch_buckets: Sequence[int] = (1, 2, 4),
                 eos_id: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 prefix_cache_pages: Optional[int] = None,
                 draft: Optional[Dict] = None,
                 ctx=None, dtype=np.float32, warmup: bool = True,
                 start: bool = True):
        from .. import ndarray as nd
        from ..models.transformer import (get_transformer_lm_catchup,
                                          get_transformer_lm_decode,
                                          get_transformer_lm_prefill)
        from ..predictor import Predictor

        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.hidden = int(hidden)
        self.max_seq_len = int(max_seq_len)
        self.head_dim = self.hidden // self.num_heads
        self.eos_id = eos_id
        self._ctx = ctx
        self._dtype = np.dtype(dtype)
        # unset knobs consult the autotuner before the env defaults:
        # explicit constructor args always pin, tuned winners beat the
        # built-in defaults, env vars remain the no-autotune fallback
        tuned = None
        if page_size is None or lane_buckets is None:
            tuned = _autotune_engine_config(
                self.num_layers, self.num_heads, self.head_dim,
                self.max_seq_len, self._dtype,
                max_lanes=(max(int(b) for b in lane_buckets)
                           if lane_buckets is not None
                           else env("MXNET_GEN_MAX_LANES", 8, int)))
        if page_size is None and tuned:
            page_size = tuned.get("page_size")
        if lane_buckets is None and tuned:
            lane_buckets = tuned.get("lane_buckets")
        self.page_size = int(env("MXNET_GEN_PAGE_SIZE", 16, int)
                             if page_size is None else page_size)
        self.num_pages = int(env("MXNET_GEN_NUM_PAGES", 128, int)
                             if num_pages is None else num_pages)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        self.lane_buckets = tuple(sorted(set(
            int(b) for b in (lane_buckets if lane_buckets is not None
                             else pow2_buckets(
                                 env("MXNET_GEN_MAX_LANES", 8, int))))))
        self.max_lanes = self.lane_buckets[-1]
        if prefill_len_buckets is None:
            prefill_len_buckets = [b for b in pow2_buckets(self.max_seq_len)
                                   if b >= min(8, self.max_seq_len)]
        self.prefill_len_buckets = tuple(sorted(set(
            int(b) for b in prefill_len_buckets)))
        self.prefill_batch_buckets = tuple(sorted(set(
            int(b) for b in prefill_batch_buckets)))
        self.max_pending = int(env("MXNET_GEN_PENDING_QUEUE", 256, int)
                               if max_pending is None else max_pending)
        self.default_max_new = env("MXNET_GEN_MAX_NEW_TOKENS", 64, int)
        self.prefix_cache_pages = max(0, int(
            env("MXNET_GEN_PREFIX_CACHE_PAGES", 0, int)
            if prefix_cache_pages is None else prefix_cache_pages))

        # -- speculative draft config (resolve K once, here) --------------
        self._draft: Optional[Dict] = None
        self._draft_params = None
        self._verify_width = 1
        if draft:
            d = dict(draft)
            d_layers = int(d.get("num_layers", max(1, self.num_layers // 2)))
            d_heads = int(d.get("num_heads", self.num_heads))
            d_hidden = int(d.get("hidden", self.hidden))
            hint = float(d.get("acceptance_hint", 0.8))
            k = d.get("k")
            if k is None:
                tuned_k = _autotune_draft_k(self.num_layers, self.hidden,
                                            d_layers, d_hidden, hint)
                k = (tuned_k.get("k") if tuned_k
                     else env("MXNET_GEN_DRAFT_K", 4, int))
            k = max(1, min(int(k), self.max_seq_len - 1))
            dparams = d.get("params")
            self._draft = {"params": dparams, "num_layers": d_layers,
                           "num_heads": d_heads, "hidden": d_hidden,
                           "k": k, "acceptance_hint": hint}
            if isinstance(dparams, str):
                dparams = nd.load(dparams)
            if dparams is None:
                raise MXNetError("draft spec needs 'params'")
            self._draft_params = dict(dparams)
            self._verify_width = k + 1
        self._accept_ewma: Optional[float] = None

        if isinstance(params, str):
            params = nd.load(params)
        # one shared copy of the weights: Predictor passes live NDArrays
        # through rebinds, so every bucket executor binds the same arrays
        self._params = dict(params)

        self.pool = PagedKVPool(self.num_pages, self.page_size,
                                self.num_layers, self.num_heads,
                                self.head_dim, dtype=self._dtype,
                                prefix_cache_pages=self.prefix_cache_pages)
        self.metrics = _GenMetrics()

        # prefill: one BucketedPredictor per prompt-length bucket.
        # Symbols build under a fresh NameManager so auto-generated op
        # names — and with them symbol.tojson(), the compile-cache graph
        # fingerprint — are independent of process construction history:
        # an engine restored from an AOT bundle must re-derive the same
        # digests the bundle was saved under.
        from ..name import NameManager

        self._prefill: Dict[int, BucketedPredictor] = {}
        for L in self.prefill_len_buckets:
            with NameManager():
                symbol = get_transformer_lm_prefill(
                    self.vocab_size, self.num_layers, self.num_heads,
                    self.hidden, seq_len=L, max_seq_len=self.max_seq_len)
            bp = BucketedPredictor(symbol, self._params, {"data": (L,)},
                                   self.prefill_batch_buckets, ctx=ctx,
                                   dtype=dtype)
            for pred in bp._preds.values():
                pred._exec._cache_kind = "gen-prefill"
            self._prefill[L] = bp

        # decode: one fixed-lane Predictor per lane bucket (shared weights
        # via reshape; pool shapes are lane-independent)
        with NameManager():
            dec_symbol = get_transformer_lm_decode(
                self.vocab_size, self.num_layers, self.num_heads,
                self.hidden, max_seq_len=self.max_seq_len,
                lanes=self.max_lanes, num_pages=self.num_pages,
                page_size=self.page_size, max_pages=self.max_pages)
        pool_shape = (self.num_pages, self.page_size, self.num_heads,
                      self.head_dim)
        shapes = {"data": (self.max_lanes,),
                  "positions": (self.max_lanes,),
                  "page_table": (self.max_lanes, self.max_pages)}
        for i in range(self.num_layers):
            shapes["layer%d_k_pool" % i] = pool_shape
            shapes["layer%d_v_pool" % i] = pool_shape
        base = Predictor(dec_symbol, self._params, shapes, ctx=ctx,
                         dtype=dtype)
        self._decode: Dict[int, Predictor] = {self.max_lanes: base}
        for b in self.lane_buckets[:-1]:
            self._decode[b] = base.reshape(
                {"data": (b,), "positions": (b,), "page_table": (b,
                 self.max_pages)})
        for pred in self._decode.values():
            pred._exec._cache_kind = "gen-step"

        # -- speculative rig: draft pool + prefill + decode, target verify
        self._draft_pool: Optional[PagedKVPool] = None
        self._draft_prefill: Dict[int, BucketedPredictor] = {}
        self._draft_decode: Dict[int, "Predictor"] = {}
        self._verify: Dict[int, "Predictor"] = {}
        if self._draft is not None:
            dl = self._draft["num_layers"]
            dh = self._draft["num_heads"]
            dhid = self._draft["hidden"]
            dhd = dhid // dh
            self._draft_pool = PagedKVPool(self.num_pages, self.page_size,
                                           dl, dh, dhd, dtype=self._dtype)
            for L in self.prefill_len_buckets:
                with NameManager():
                    symbol = get_transformer_lm_prefill(
                        self.vocab_size, dl, dh, dhid, seq_len=L,
                        max_seq_len=self.max_seq_len)
                bp = BucketedPredictor(symbol, self._draft_params,
                                       {"data": (L,)},
                                       self.prefill_batch_buckets,
                                       ctx=ctx, dtype=dtype)
                for pred in bp._preds.values():
                    pred._exec._cache_kind = "gen-draft-prefill"
                self._draft_prefill[L] = bp
            with NameManager():
                dd_symbol = get_transformer_lm_decode(
                    self.vocab_size, dl, dh, dhid,
                    max_seq_len=self.max_seq_len, lanes=self.max_lanes,
                    num_pages=self.num_pages, page_size=self.page_size,
                    max_pages=self.max_pages)
            d_pool_shape = (self.num_pages, self.page_size, dh, dhd)
            d_shapes = {"data": (self.max_lanes,),
                        "positions": (self.max_lanes,),
                        "page_table": (self.max_lanes, self.max_pages)}
            for i in range(dl):
                d_shapes["layer%d_k_pool" % i] = d_pool_shape
                d_shapes["layer%d_v_pool" % i] = d_pool_shape
            d_base = Predictor(dd_symbol, self._draft_params, d_shapes,
                               ctx=ctx, dtype=dtype)
            self._draft_decode = {self.max_lanes: d_base}
            for b in self.lane_buckets[:-1]:
                self._draft_decode[b] = d_base.reshape(
                    {"data": (b,), "positions": (b,),
                     "page_table": (b, self.max_pages)})
            for pred in self._draft_decode.values():
                pred._exec._cache_kind = "gen-draft-step"
            # verification is teacher forcing too — the draft's K
            # proposals are known before the call — so the verify rig
            # uses the same windowed single-pass graph as catch-up
            # rather than chaining K+1 literal decode blocks (whose
            # dispatch cost eats the speculation win on small models)
            with NameManager():
                v_symbol = get_transformer_lm_catchup(
                    self.vocab_size, self.num_layers, self.num_heads,
                    self.hidden, max_seq_len=self.max_seq_len,
                    lanes=self.max_lanes, num_pages=self.num_pages,
                    page_size=self.page_size, max_pages=self.max_pages,
                    width=self._verify_width)
            v_shapes = {"data": (self.max_lanes, self._verify_width),
                        "positions": (self.max_lanes, self._verify_width),
                        "page_table": (self.max_lanes, self.max_pages)}
            for i in range(self.num_layers):
                v_shapes["layer%d_k_pool" % i] = pool_shape
                v_shapes["layer%d_v_pool" % i] = pool_shape
            v_base = Predictor(v_symbol, self._params, v_shapes, ctx=ctx,
                               dtype=dtype)
            self._verify = {self.max_lanes: v_base}
            for b in self.lane_buckets[:-1]:
                self._verify[b] = v_base.reshape(
                    {"data": (b, self._verify_width),
                     "positions": (b, self._verify_width),
                     "page_table": (b, self.max_pages)})
            for pred in self._verify.values():
                pred._exec._cache_kind = "gen-verify"

        # -- prefix-cache catch-up rig: a windowed teacher-forcing
        # executable that re-walks the KNOWN suffix of a partial prefix
        # hit (or a re-admitted preemptee) ``catchup_width`` slots per
        # forward instead of one per decode iteration, so cached
        # admissions reach their first token in one decode step no
        # matter where the index's page-granular match stopped
        self._catchup: Dict[int, "Predictor"] = {}
        self._catchup_width = 0
        if self.prefix_cache_pages:
            # wide enough to swallow a typical page-rounding suffix in
            # one forward — every extra round pays a full pool
            # host-roundtrip plus the executable's fixed dispatch cost;
            # the windowed pass itself is compute-proportional, so a
            # wider window costs only the pad slots it doesn't use
            cw = max(2, min(32, self.max_seq_len - 1))
            self._catchup_width = cw
            with NameManager():
                c_symbol = get_transformer_lm_catchup(
                    self.vocab_size, self.num_layers, self.num_heads,
                    self.hidden, max_seq_len=self.max_seq_len,
                    lanes=self.max_lanes, num_pages=self.num_pages,
                    page_size=self.page_size, max_pages=self.max_pages,
                    width=cw)
            c_shapes = {"data": (self.max_lanes, cw),
                        "positions": (self.max_lanes, cw),
                        "page_table": (self.max_lanes, self.max_pages)}
            for i in range(self.num_layers):
                c_shapes["layer%d_k_pool" % i] = pool_shape
                c_shapes["layer%d_v_pool" % i] = pool_shape
            c_base = Predictor(c_symbol, self._params, c_shapes, ctx=ctx,
                               dtype=dtype)
            self._catchup = {self.max_lanes: c_base}
            for b in self.lane_buckets[:-1]:
                self._catchup[b] = c_base.reshape(
                    {"data": (b, cw), "positions": (b, cw),
                     "page_table": (b, self.max_pages)})
            for pred in self._catchup.values():
                pred._exec._cache_kind = "gen-catchup"

        # recompile-detector bookkeeping: lane buckets warmup compiled,
        # post-warmup steps that hit a novel (never-warmed) bucket
        self.warmed_lane_buckets = set()
        self._warned_lane_buckets = set()
        self.decode_cold_runs = 0

        self._cv = threading.Condition()
        self._pending: deque = deque()  # _Seq, FIFO (preempted go front)
        self._active: List[_Seq] = []
        self._sid = 0
        self._closed = False
        self._drain = True
        self._loop_thread = threading.Thread(
            target=self._loop, name="mxtpu-gen-engine", daemon=True)
        self._started = False
        if warmup:
            self.warmup()
        if start:
            self.start()

    # -- construction helpers ---------------------------------------------
    def spec(self) -> Dict:
        """Model/engine geometry needed to rebuild this engine against a
        new checkpoint (hot-swap, AOT warmup manifests, shadow replicas).
        The draft block carries the RESOLVED speculative K — a replica
        rebuilt from a bundle speculates with zero re-tuning."""
        out = {
            "vocab_size": self.vocab_size, "num_layers": self.num_layers,
            "num_heads": self.num_heads, "hidden": self.hidden,
            "max_seq_len": self.max_seq_len,
            "lane_buckets": list(self.lane_buckets),
            "page_size": self.page_size, "num_pages": self.num_pages,
            "prefill_len_buckets": list(self.prefill_len_buckets),
            "prefill_batch_buckets": list(self.prefill_batch_buckets),
            "eos_id": self.eos_id, "max_pending": self.max_pending,
            "prefix_cache_pages": self.prefix_cache_pages,
        }
        if self._draft is not None:
            out["draft"] = dict(self._draft)
        return out

    @classmethod
    def from_checkpoint(cls, prefix, epoch, **spec):
        """Build from ``save_checkpoint`` files; ``spec`` as for the
        constructor (see :meth:`spec`)."""
        return cls("%s-%04d.params" % (prefix, int(epoch)), **spec)

    def warmup(self):
        """Pre-compile every prefill (length x batch) bucket and every
        decode/draft/verify lane bucket, priming through the compile
        cache when it is enabled — post-warmup steady state performs
        ZERO XLA compiles, and an attached AOT bundle makes warmup
        deserialize-only."""
        for bp in self._prefill.values():
            bp.warmup()
        for bp in self._draft_prefill.values():
            bp.warmup()
        pool_shape = (self.num_pages, self.page_size, self.num_heads,
                      self.head_dim)
        zero_pool = np.zeros(pool_shape, self._dtype)
        d_zero_pool = None
        if self._draft is not None:
            d_zero_pool = np.zeros(
                (self.num_pages, self.page_size, self._draft["num_heads"],
                 self._draft["hidden"] // self._draft["num_heads"]),
                self._dtype)
        for b in self.lane_buckets:
            rigs = [(self._decode[b], (b,), self.num_layers, zero_pool)]
            if self._draft is not None:
                rigs.append((self._draft_decode[b], (b,),
                             self._draft["num_layers"], d_zero_pool))
                rigs.append((self._verify[b], (b, self._verify_width),
                             self.num_layers, zero_pool))
            if self._catchup:
                rigs.append((self._catchup[b], (b, self._catchup_width),
                             self.num_layers, zero_pool))
            for pred, dshape, n_layers, zpool in rigs:
                pred.set_input("data", np.zeros(dshape, self._dtype))
                pred.set_input("positions", np.zeros(dshape, self._dtype))
                pred.set_input("page_table",
                               np.zeros((b, self.max_pages), self._dtype))
                for i in range(n_layers):
                    pred.set_input("layer%d_k_pool" % i, zpool)
                    pred.set_input("layer%d_v_pool" % i, zpool)
                pred._exec.forward(is_train=False)
                for out in pred.get_outputs():
                    out.asnumpy()  # block until compiled + ran
            self.warmed_lane_buckets.add(b)
        return self

    def compiled_entries(self):
        """Primed compile-cache wrappers across prefill, decode, draft,
        verify and catch-up executors (kinds ``gen-prefill`` /
        ``gen-step`` / ``gen-draft-prefill`` / ``gen-draft-step`` /
        ``gen-verify`` / ``gen-catchup``) —
        the input to ``checkpoint.save_aot_bundle`` so an autoscaled
        replica serves its first generate request with zero cold
        compiles."""
        from ..compile_cache import CachedFunction

        out = []
        for bp in list(self._prefill.values()) + \
                list(self._draft_prefill.values()):
            out.extend(bp.compiled_entries())
        preds = (list(self._decode.values())
                 + list(self._draft_decode.values())
                 + list(self._verify.values())
                 + list(self._catchup.values()))
        for pred in preds:
            for fn in pred._exec._jit_cache.values():
                if isinstance(fn, CachedFunction):
                    out.append(fn)
        return out

    def cold_decode_runs(self) -> int:
        """Post-warmup decode steps that hit a never-warmed lane bucket
        plus cold prefill flushes — 0 is the "steady state never
        recompiles" acceptance check."""
        return (self.decode_cold_runs
                + sum(bp.cold_runs for bp in self._prefill.values())
                + sum(bp.cold_runs
                      for bp in self._draft_prefill.values()))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if not self._started:
            self._started = True
            self._loop_thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Stop the engine.  With ``drain`` (default) queued and active
        sequences finish first (bounded by ``timeout`` seconds), without
        it they fail fast with :class:`ServerClosedError`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._drain = drain
            if not drain:
                self._fail_all_locked(ServerClosedError(
                    "engine stopped before completion"))
            self._cv.notify_all()
        if self._started:
            self._loop_thread.join(timeout)
        with self._cv:
            # drain deadline expired with work outstanding (or fail-fast
            # stop racing the loop): cancel whatever is left
            self._fail_all_locked(ServerClosedError("engine stopped"))
        # observed-acceptance feedback: when the measured EWMA drifts a
        # decile from the configured hint, pre-record the draft_k winner
        # for the observed rate so the NEXT construction (same geometry,
        # honest hint) resolves without tuning from the stale prior
        if self._draft is not None and self._accept_ewma is not None:
            if abs(self._accept_ewma
                   - self._draft["acceptance_hint"]) >= 0.1:
                try:
                    _autotune_draft_k(
                        self.num_layers, self.hidden,
                        self._draft["num_layers"], self._draft["hidden"],
                        self._accept_ewma)
                except Exception:
                    pass

    def handoff(self) -> int:
        """Preempt every queued and active stream WITHOUT stopping the
        engine: each fails with :class:`ServerClosedError`, which a
        router-level consumer treats as a replica failure and re-submits
        (prompt + emitted tokens) on a surviving replica — greedy decode
        makes the resumed transcript bit-identical.  The graceful
        page-out handoff: call this before the owning server releases
        its device memory.  Returns the number of streams handed off."""
        with self._cv:
            n = len(self._pending) + len(self._active)
            self._fail_all_locked(ServerClosedError(
                "replica preempted: stream handed off"))
            self._cv.notify_all()
        if n:
            _telemetry.log_event("gen_handoff", streams=n)
        return n

    def _fail_all_locked(self, exc):
        n = 0
        for seq in list(self._pending) + list(self._active):
            self.pool.free(seq.sid)
            if self._draft_pool is not None:
                self._draft_pool.free(seq.sid)
            seq.stream._finish(exc)
            n += 1
        self._pending.clear()
        del self._active[:]
        if n:
            self.metrics.failed.inc(n)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)

    # -- request path ------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenStream:
        """Queue one prompt for generation; returns its
        :class:`GenStream`.  Raises :class:`QueueFullError` when the
        pending queue is at capacity (HTTP 429 — retry with backoff) and
        :class:`MXNetError` for prompts that can never fit."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise MXNetError("empty prompt")
        max_new = int(self.default_max_new if max_new_tokens is None
                      else max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new
        if total > self.max_seq_len:
            raise MXNetError(
                "prompt (%d) + max_new_tokens (%d) exceeds max_seq_len %d"
                % (len(prompt), max_new, self.max_seq_len))
        if self.pool.pages_for(total) > self.pool.capacity:
            raise MXNetError(
                "request needs %d KV pages but the pool only has %d — it "
                "can never be admitted" %
                (self.pool.pages_for(total), self.pool.capacity))
        stream = GenStream(prompt, max_new)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        with self._cv:
            if self._closed:
                raise ServerClosedError("engine is stopped")
            if len(self._pending) >= self.max_pending:
                self.metrics.rejected.inc()
                raise QueueFullError(
                    "generation queue full (%d pending); retry with "
                    "backoff" % len(self._pending))
            self._pending.append(_Seq(self._sid, stream, deadline,
                                      self.eos_id))
            self._sid += 1
            self.metrics.g_pending.set(len(self._pending))
            self._cv.notify_all()
        return stream

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None,
                 timeout: Optional[float] = 300.0) -> List[int]:
        """Blocking convenience wrapper: the full generated token list."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    def pending_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def active_lanes(self) -> int:
        with self._cv:
            return len(self._active)

    def snapshot(self) -> dict:
        with self._cv:
            snap = {"pending": len(self._pending),
                    "active": len(self._active),
                    "tokens_total": self.metrics.tokens.value,
                    "cold_decode_runs": self.cold_decode_runs(),
                    "prefix_cache_pages": self.prefix_cache_pages,
                    "kv": self.pool.snapshot()}
            if self._draft is not None:
                snap["draft"] = {
                    "k": self._draft["k"],
                    "proposed": self.metrics.draft_proposed.value,
                    "accepted": self.metrics.draft_accepted.value,
                    "accept_rate_ewma": self._accept_ewma,
                    "fallbacks": self.metrics.spec_fallbacks.value,
                    "kv": self._draft_pool.snapshot(),
                }
            return snap

    # -- engine loop -------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._active \
                        and not self._closed:
                    self._cv.wait(0.05)
                if self._closed and not self._active and \
                        (not self._pending or not self._drain):
                    for seq in self._pending:
                        seq.stream._finish(ServerClosedError(
                            "engine stopped before execution"))
                    self._pending.clear()
                    return
            try:
                self._admit()
                if self._active:
                    self._decode_step()
            except BaseException as exc:  # fault-injected or real: contain
                logging.warning("generation engine step failed: %r", exc)
                with self._cv:
                    self._fail_all_locked(exc)
                _telemetry.log_event("gen_engine_error", error=repr(exc))

    def _prefill_bucket_for(self, n: int) -> int:
        for L in self.prefill_len_buckets:
            if L >= n:
                return L
        raise MXNetError("prompt of %d exceeds largest prefill bucket %d"
                         % (n, self.prefill_len_buckets[-1]))

    def _admit(self):
        """Move pending sequences into free decode lanes: allocate KV
        pages (resolving the prompt against the prefix index), run
        bucketed prefill for the cache misses, stream each prefilled
        sequence's first token.  Cached sequences go straight to decode
        lanes — zero prefill steps."""
        batch: List[_Seq] = []
        now = time.monotonic()
        avail = self.pool.reclaimable_pages()
        d_avail = (self._draft_pool.free_pages()
                   if self._draft_pool is not None else None)
        with self._cv:
            while self._pending and \
                    len(self._active) + len(batch) < self.max_lanes:
                seq = self._pending[0]
                if seq.deadline is not None and now > seq.deadline:
                    self._pending.popleft()
                    self.metrics.expired.inc()
                    seq.stream._finish(DeadlineExceededError(
                        "request waited past its TTFT deadline"))
                    continue
                need = self.pool.pages_for(len(seq.tokens))
                if need > avail or (d_avail is not None and need > d_avail):
                    break  # wait for active lanes to retire/free pages
                avail -= need
                if d_avail is not None:
                    d_avail -= need
                self._pending.popleft()
                batch.append(seq)
            self.metrics.g_pending.set(len(self._pending))
        if not batch:
            return
        faults.fire("generation.engine.admit")
        # group by prompt-length bucket, chunk to the prefill batch cap
        by_bucket: Dict[int, List[_Seq]] = {}
        for seq in batch:
            by_bucket.setdefault(
                self._prefill_bucket_for(len(seq.tokens)), []).append(seq)
        for L, seqs in sorted(by_bucket.items()):
            bp = self._prefill[L]
            cap = bp.max_batch_size
            for ofs in range(0, len(seqs), cap):
                self._prefill_group(L, seqs[ofs:ofs + cap])

    def _prefill_group(self, L: int, seqs: List[_Seq]):
        admitted: List[_Seq] = []
        for seq in seqs:
            try:
                _, cached = self.pool.alloc_prefix(
                    seq.sid, len(seq.tokens),
                    tokens=(seq.tokens if self.prefix_cache_pages
                            else None))
            except KVPoolExhaustedError:
                # admission raced a concurrent consumer: wait a round
                with self._cv:
                    self._pending.appendleft(seq)
                continue
            if self._draft_pool is not None:
                try:
                    self._draft_pool.alloc(seq.sid, len(seq.tokens))
                except KVPoolExhaustedError:
                    self.pool.free(seq.sid)
                    with self._cv:
                        self._pending.appendleft(seq)
                    continue
            seq.next_pos = cached  # 0 on a miss: full prefill below
            if cached:
                seq.stream.cached_prefix_tokens += cached
                self.metrics.cached_admissions.inc()
            admitted.append(seq)
        if not admitted:
            return
        # the draft holds no prefix cache: prefill EVERY admitted
        # sequence through the draft model so proposals can start from
        # the first decode iteration
        if self._draft is not None:
            dbp = self._draft_prefill[L]
            items = []
            for seq in admitted:
                buf = np.zeros((L,), self._dtype)
                buf[:len(seq.tokens)] = seq.tokens
                items.append({"data": buf})
            _, results = dbp.forward_batch(items)
            for seq, outs in zip(admitted, results):
                n = len(seq.tokens)
                for layer in range(self._draft["num_layers"]):
                    self._draft_pool.write_prefill(
                        seq.sid, layer, outs[1 + 2 * layer],
                        outs[2 + 2 * layer], n)
                seq.draft_pos = n
        misses = [s for s in admitted if s.next_pos == 0]
        if misses:
            bp = self._prefill[L]
            items = []
            for seq in misses:
                buf = np.zeros((L,), self._dtype)
                buf[:len(seq.tokens)] = seq.tokens
                items.append({"data": buf})
            _, results = bp.forward_batch(items)
            for seq, outs in zip(misses, results):
                n = len(seq.tokens)
                logits = outs[0]  # (L, vocab)
                for layer in range(self.num_layers):
                    self.pool.write_prefill(seq.sid, layer,
                                            outs[1 + 2 * layer],
                                            outs[2 + 2 * layer], n)
                seq.stream.prefill_tokens += n
                seq.next_pos = n
                if self.prefix_cache_pages:
                    self.pool.register_prefix(seq.sid, seq.tokens[:n])
                tok = int(np.argmax(logits[n - 1]))
                self._emit(seq, tok)
        if self.prefix_cache_pages:
            self._catchup_group([s for s in admitted if s not in misses])
        for seq in admitted:
            seq.admitted_at = time.monotonic()
        with self._cv:
            self._active.extend(s for s in admitted
                                if not s.stream.done)
            self.metrics.admitted.inc(len(admitted))
            self.metrics.g_active.set(len(self._active))

    def _catchup_group(self, seqs: List[_Seq]):
        """Batch-walk the KNOWN suffix of prefix hits through the
        windowed catch-up executable — ``catchup_width`` positions
        per forward instead of one per decode iteration — feeding
        THROUGH the final prompt position and emitting the first
        generated token from the last slot's logits.  A cached
        admission therefore reaches its first token inside admission,
        in ``ceil(suffix / catchup_width)`` forwards, with no separate
        decode step: TTFT stays one engine iteration regardless of how
        far short of the prompt the index's page-granular match fell."""
        pending = [s for s in seqs
                   if 0 < s.next_pos < len(s.tokens)]
        if not pending or not self._catchup:
            return
        W = self._catchup_width
        while pending:
            b = self._lane_bucket_for(len(pending))
            self._note_lane_bucket(b)
            pred = self._catchup[b]
            data = np.zeros((b, W), self._dtype)
            # pads park in the scratch page's last slot (zero table row)
            positions = np.full((b, W), self.max_seq_len - 1,
                                dtype=self._dtype)
            table = np.zeros((b, self.max_pages), self._dtype)
            spans = []
            for i, seq in enumerate(pending):
                # the cursor's page can still be prefix-indexed/shared
                self.pool.ensure_writable(seq.sid, seq.next_pos)
                span = min(W, len(seq.tokens) - seq.next_pos)
                data[i, :span] = seq.tokens[seq.next_pos:
                                            seq.next_pos + span]
                positions[i, :span] = np.arange(seq.next_pos,
                                                seq.next_pos + span)
                table[i] = self.pool.page_table_row(seq.sid,
                                                    self.max_pages)
                spans.append(span)
            outs = self._run_lanes(pred, self.num_layers, self.pool,
                                   data, positions, table)
            logits = outs[0].reshape(b, W, -1)  # (lanes, width, vocab)
            nxt = []
            for i, (seq, span) in enumerate(zip(pending, spans)):
                seq.iters += 1
                seq.next_pos += span
                self.pool.register_prefix(seq.sid,
                                          seq.tokens[:seq.next_pos])
                if seq.next_pos >= len(seq.tokens):
                    # crossed into generation: the last fed slot's
                    # logits seed the stream's first token
                    self._emit(seq, int(np.argmax(logits[i, span - 1])))
                else:
                    nxt.append(seq)
            pending = nxt

    def _emit(self, seq: _Seq, tok: int):
        """Stream one generated token; retires the sequence when it hit
        its budget or EOS.  Returns True when the sequence retired."""
        first = not seq.stream.tokens
        gap = seq.stream._emit(tok)
        if first:
            seq.stream.ttft_iters = seq.iters
        seq.tokens.append(tok)
        seq.gen_count += 1
        self.metrics.tokens.inc()
        (self.metrics.ttft if first else self.metrics.itl).observe(gap)
        if seq.gen_count >= seq.max_new or \
                (seq.eos_id is not None and tok == seq.eos_id):
            self._retire(seq)
            return True
        return False

    def _retire(self, seq: _Seq):
        faults.fire("generation.engine.retire")
        if self.prefix_cache_pages:
            # publish the finished transcript's complete pages before
            # releasing them: a refcount-0 indexed page is retained as
            # cache, so the next request sharing this prefix hits
            self.pool.register_prefix(seq.sid, seq.tokens[:seq.next_pos])
        self.pool.free(seq.sid)
        if self._draft_pool is not None:
            self._draft_pool.free(seq.sid)
        seq.stream._finish(None)
        self.metrics.retired.inc()

    def _preempt_one(self, exclude: Optional[_Seq] = None) -> bool:
        """Free the youngest active lane's pages and push the sequence
        back to the FRONT of the pending queue for re-admission of
        prompt + generated-so-far (greedy decode is deterministic, so
        its stream continues without a hiccup).  Its complete pages are
        published to the prefix index first, so with caching enabled
        the re-admission is a prefix HIT instead of a full re-prefill."""
        with self._cv:
            victims = [s for s in self._active if s is not exclude]
            if not victims:
                victims = [s for s in self._active]
            if not victims:
                return False
            victim = max(victims, key=lambda s: s.admitted_at)
            self._active.remove(victim)
            self._pending.appendleft(victim)
            self.metrics.g_active.set(len(self._active))
            self.metrics.g_pending.set(len(self._pending))
        if self.prefix_cache_pages:
            self.pool.register_prefix(victim.sid,
                                      victim.tokens[:victim.next_pos])
        self.pool.free(victim.sid)
        if self._draft_pool is not None:
            self._draft_pool.free(victim.sid)
        victim.next_pos = 0
        victim.draft_pos = 0
        self.metrics.preempted.inc()
        _telemetry.log_event("gen_preempt", sid=victim.sid,
                             tokens=len(victim.tokens))
        return True

    def _lane_bucket_for(self, n: int) -> int:
        for b in self.lane_buckets:
            if b >= n:
                return b
        raise MXNetError("%d active lanes exceed largest bucket %d"
                         % (n, self.lane_buckets[-1]))

    def _note_lane_bucket(self, b: int):
        if b in self.warmed_lane_buckets:
            return
        self.decode_cold_runs += 1
        self.metrics.cold_steps.inc()
        self.warmed_lane_buckets.add(b)
        if b not in self._warned_lane_buckets:
            self._warned_lane_buckets.add(b)
            logging.warning(
                "generation: decode step hit never-warmed lane bucket "
                "%d post-warmup (fresh XLA compile on the serving "
                "path) — add it to lane_buckets/warmup", b)
            _telemetry.log_event("gen_decode_cold_bucket", lanes=b)

    def _decode_step(self):
        """One continuous-batching iteration: grow every lane's KV
        allocation for the positions about to be written (pool
        exhaustion preempts the youngest other lane), copy-on-write any
        shared page under the feed cursor, then advance every lane —
        one token via the decode executable, or up to K+1 via the
        draft/verify speculative pass."""
        faults.fire("generation.engine.step")
        width = self._verify_width
        for seq in list(self._active):
            # an earlier lane's extend may have preempted this one already
            while seq in self._active:
                try:
                    tgt = min(seq.next_pos + width, seq.limit,
                              self.max_seq_len)
                    self.pool.extend(seq.sid, tgt)
                    if self.prefix_cache_pages:
                        # the page under the cursor may be shared (cached
                        # admission) or still prefix-indexed: split it
                        # before this iteration writes K/V there
                        self.pool.ensure_writable(seq.sid, seq.next_pos)
                    if self._draft_pool is not None:
                        self._draft_pool.extend(seq.sid, tgt)
                    break
                except KVPoolExhaustedError:
                    if not self._preempt_one(exclude=seq):
                        raise
        active = list(self._active)
        if not active:
            return
        if self._draft is not None:
            self._spec_step(active)
        else:
            self._plain_step(active)

    def _run_lanes(self, pred, n_layers, pool, data, positions, table):
        """Bind one lane-bucket executable, run it, write the pool
        planes back, return the raw outputs."""
        pred.set_input("data", data)
        pred.set_input("positions", positions)
        pred.set_input("page_table", table)
        for i in range(n_layers):
            pred.set_input("layer%d_k_pool" % i, pool.k_pools[i])
            pred.set_input("layer%d_v_pool" % i, pool.v_pools[i])
        pred._exec.forward(is_train=False)
        outs = [o.asnumpy() for o in pred.get_outputs()]
        n_logits = len(outs) - 2 * n_layers
        for i in range(n_layers):
            np.copyto(pool.k_pools[i], outs[n_logits + 2 * i])
            np.copyto(pool.v_pools[i], outs[n_logits + 2 * i + 1])
        return outs

    def _plain_step(self, active: List[_Seq]):
        """Advance every active lane one position through the decode
        executable: feed ``tokens[next_pos]`` at ``next_pos``, emit the
        argmax only when the cursor crosses into generation (a lane
        re-walking a known suffix — partial cache hit, re-admitted
        preemptee — just materializes K/V silently)."""
        b = self._lane_bucket_for(len(active))
        self._note_lane_bucket(b)
        pred = self._decode[b]
        data = np.zeros((b,), self._dtype)
        positions = np.zeros((b,), self._dtype)
        table = np.zeros((b, self.max_pages), self._dtype)
        for i, seq in enumerate(active):
            data[i] = seq.tokens[seq.next_pos]
            positions[i] = seq.next_pos  # slot the new K/V lands in
            table[i] = self.pool.page_table_row(seq.sid, self.max_pages)
        outs = self._run_lanes(pred, self.num_layers, self.pool,
                               data, positions, table)
        logits = outs[0]
        self.metrics.steps.inc()
        retired = []
        for i, seq in enumerate(active):
            seq.iters += 1
            seq.next_pos += 1
            if self.prefix_cache_pages:
                self.pool.register_prefix(seq.sid,
                                          seq.tokens[:seq.next_pos])
            if seq.next_pos >= len(seq.tokens):
                if self._emit(seq, int(np.argmax(logits[i]))):
                    retired.append(seq)
        self._drop_retired(retired)

    def _spec_step(self, active: List[_Seq]):
        """One speculative iteration: draft K proposals per steady lane,
        then ONE windowed target verify pass scores feed slots
        ``[tokens[next_pos], d_1 .. d_K]`` at positions ``next_pos ..
        next_pos+K`` (teacher forcing — every feed token is known before
        the call, so the graph is the same single causal pass as
        catch-up, not K+1 chained decode blocks).  Greedy acceptance
        walks the slots in order, keeping every emitted argmax whose
        following draft feed matches — the emitted tokens are the
        TARGET's own argmaxes over the same paged K/V a plain decode
        would read, and the spec-parity tests assert transcript equality
        against non-speculative greedy for every K.  A fault at
        ``generation.draft.verify`` degrades THIS iteration to a plain
        single-token step instead of failing any stream."""
        width = self._verify_width
        b = self._lane_bucket_for(len(active))
        self._note_lane_bucket(b)
        try:
            faults.fire("generation.draft.verify")
        except Exception:
            self.metrics.spec_fallbacks.inc()
            self._plain_step(active)
            return
        proposals = self._draft_propose(active, b)
        vpred = self._verify[b]
        data = np.zeros((b, width), self._dtype)
        # pad slots park at (token 0, position max_seq_len-1): with a
        # zero page-table row beyond the lane's allocation the write
        # lands in scratch page 0, and no live position ever attends it
        positions = np.full((b, width), self.max_seq_len - 1, self._dtype)
        table = np.zeros((b, self.max_pages), self._dtype)
        lane_width: Dict[object, int] = {}
        for i, seq in enumerate(active):
            table[i] = self.pool.page_table_row(seq.sid, self.max_pages)
            drafts = proposals.get(seq.sid, [])
            lw = 0
            for w in range(width):
                p = seq.next_pos + w
                if p >= min(seq.limit, self.max_seq_len):
                    break
                if p < len(seq.tokens):
                    tok = seq.tokens[p]
                else:
                    j = w - (len(seq.tokens) - seq.next_pos)
                    if j < 0 or j >= len(drafts):
                        break
                    tok = drafts[j]
                data[i, w] = tok
                positions[i, w] = p
                lw += 1
            lane_width[seq.sid] = lw
        outs = self._run_lanes(vpred, self.num_layers, self.pool,
                               data, positions, table)
        logits = outs[0].reshape(b, width, -1)
        self.metrics.steps.inc()
        retired = []
        for i, seq in enumerate(active):
            seq.iters += 1
            lw = lane_width[seq.sid]
            n_drafted = max(0, lw - (len(seq.tokens) - seq.next_pos))
            start = seq.next_pos
            emits = 0
            for w in range(lw):
                g = int(np.argmax(logits[i, w]))
                seq.next_pos = start + w + 1
                if seq.next_pos < len(seq.tokens):
                    continue  # known-suffix slot: K/V only, no emission
                emits += 1
                if self._emit(seq, g):
                    retired.append(seq)
                    break
                if w + 1 < lw and int(data[i, w + 1]) != g:
                    break  # draft diverged: discard the rest
            if self.prefix_cache_pages:
                self.pool.register_prefix(seq.sid,
                                          seq.tokens[:seq.next_pos])
            # the draft pool holds accepted-token K/V below next_pos and
            # rejected junk above it: snap the cursor back so the next
            # sync round re-feeds only what the target actually kept
            seq.draft_pos = seq.next_pos
            if n_drafted:
                accepted = max(0, emits - 1)
                self.metrics.draft_proposed.inc(n_drafted)
                self.metrics.draft_accepted.inc(accepted)
                rate = accepted / float(n_drafted)
                st = seq.stream
                st.draft_proposed += n_drafted
                st.draft_accepted += accepted
                st.accept_rate = (rate if st.accept_rate is None
                                  else 0.8 * st.accept_rate + 0.2 * rate)
                self._accept_ewma = (rate if self._accept_ewma is None
                                     else 0.8 * self._accept_ewma
                                     + 0.2 * rate)
                self.metrics.g_accept.set(self._accept_ewma)
        self._drop_retired(retired)

    def _draft_propose(self, active: List[_Seq], b: int) -> Dict:
        """Run the draft model: first catch its pool up to each lane's
        feed cursor (re-feeding accepted tokens its last rejected run
        clobbered), then K batched rounds of chained greedy proposals
        for every steady lane.  Returns {sid: [d_1 .. d_K]}."""
        k = self._verify_width - 1
        pred = self._draft_decode[b]
        dl = self._draft["num_layers"]
        rows = {s.sid: self._draft_pool.page_table_row(s.sid,
                                                       self.max_pages)
                for s in active}
        while True:
            lag = [s for s in active if s.draft_pos < s.next_pos]
            if not lag:
                break
            data = np.zeros((b,), self._dtype)
            positions = np.full((b,), self.max_seq_len - 1, self._dtype)
            table = np.zeros((b, self.max_pages), self._dtype)
            for i, seq in enumerate(active):
                if seq.draft_pos < seq.next_pos:
                    data[i] = seq.tokens[seq.draft_pos]
                    positions[i] = seq.draft_pos
                    table[i] = rows[seq.sid]
            self._run_lanes(pred, dl, self._draft_pool,
                            data, positions, table)
            for seq in lag:
                seq.draft_pos += 1
        proposals: Dict[object, List[int]] = {}
        feed: Dict[object, int] = {}
        for seq in active:
            if seq.next_pos == len(seq.tokens) - 1:
                proposals[seq.sid] = []
                feed[seq.sid] = seq.tokens[seq.next_pos]
        if not proposals:
            return proposals
        for r in range(k):
            data = np.zeros((b,), self._dtype)
            positions = np.full((b,), self.max_seq_len - 1, self._dtype)
            table = np.zeros((b, self.max_pages), self._dtype)
            live = []
            for i, seq in enumerate(active):
                if seq.sid not in proposals:
                    continue
                p = seq.next_pos + r
                if p >= min(seq.limit, self.max_seq_len) - 1:
                    continue  # no use drafting past the hard stop
                data[i] = feed[seq.sid]
                positions[i] = p
                table[i] = rows[seq.sid]
                live.append((i, seq))
            if not live:
                break
            outs = self._run_lanes(pred, dl, self._draft_pool,
                                   data, positions, table)
            logits = outs[0]
            for i, seq in live:
                d = int(np.argmax(logits[i]))
                proposals[seq.sid].append(d)
                feed[seq.sid] = d
        return proposals

    def _drop_retired(self, retired: List[_Seq]):
        if not retired:
            return
        with self._cv:
            for seq in retired:
                if seq in self._active:
                    self._active.remove(seq)
            self.metrics.g_active.set(len(self._active))
            self._cv.notify_all()

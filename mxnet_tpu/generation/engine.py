"""DecodeEngine — iteration-level continuous batching over paged KV.

Autoregressive serving has two phases with opposite shapes: *prefill*
(one big parallel pass over the prompt) and *decode* (one token per
sequence per step, forever).  Request-level batching couples both to
the slowest member of a batch; iteration-level ("continuous") batching
instead re-forms the batch EVERY decode step — new sequences are
admitted into free lanes the moment prefill finishes, finished ones
retire immediately — so short requests never wait for long ones and
the decode executable stays saturated (Orca / vLLM, PAPERS.md).

XLA discipline: every XLA-visible shape here is static.

* Prefill runs through one :class:`~mxnet_tpu.serving.batcher.
  BucketedPredictor` per prompt-length bucket (pow2 lengths), i.e. the
  same shape-quantized executables the scoring tier uses.
* Decode is a fixed-lane slotted program (``models.transformer.
  get_transformer_lm_decode``): ``lanes`` sequences advance one token
  through per-lane page tables into a shared paged KV pool
  (:mod:`.kv_pool`), compiled ONCE per lane-count bucket and primed
  through the PR 10 compile cache (entry kind ``gen-step`` /
  ``gen-prefill``), so AOT bundles restore a generate-ready replica
  with zero cold compiles.

Backpressure: admission is a bounded pending queue (reject =
:class:`~mxnet_tpu.serving.batcher.QueueFullError`, the HTTP 429/503
contract) plus KV-pool capacity; a mid-decode pool exhaustion preempts
the youngest lane (its pages are freed, the sequence re-queues for
re-prefill of prompt+generated — greedy decode is deterministic, so
the stream continues seamlessly), which bounds memory without ever
deadlocking.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import faults
from .. import telemetry as _telemetry
from ..base import MXNetError, env, register_env
from ..serving.batcher import (BucketedPredictor, DeadlineExceededError,
                               QueueFullError, ServerClosedError,
                               pow2_buckets)
from .kv_pool import KVPoolExhaustedError, PagedKVPool

__all__ = ["DecodeEngine", "GenStream"]


def _autotune_engine_config(num_layers, num_heads, head_dim, max_seq_len,
                            dtype, max_lanes):
    """Tuned {lane_buckets, page_size} for this model geometry, or None.

    The objective is analytic and deterministic — no lowering: expected
    padded-lane waste under uniform live-lane demand, KV fragmentation
    of a half page per sequence, a per-bucket compile-cost term (every
    lane bucket is one more decode executable to build and keep warm)
    and a page-table-length term penalizing tiny pages."""
    try:
        from .. import autotune
    except Exception:
        return None
    if not autotune.enabled():
        return None
    key = {"num_layers": int(num_layers), "num_heads": int(num_heads),
           "head_dim": int(head_dim), "max_seq_len": int(max_seq_len),
           "max_lanes": int(max_lanes), "dtype": str(np.dtype(dtype))}

    def score(cand):
        buckets = sorted(int(b) for b in cand["lane_buckets"])
        page = int(cand["page_size"])
        waste = 0.0
        for n in range(1, max_lanes + 1):
            b = next((b for b in buckets if b >= n), buckets[-1])
            waste += (b - n) / float(b)
        waste /= max_lanes
        frag = (page - 1) / 2.0 / max(1.0, max_seq_len / 2.0)
        return (waste + frag + 0.02 * len(buckets)
                + 0.0005 * (max_seq_len / float(page)))

    return autotune.get_or_tune(
        "decode_engine", key,
        candidates=autotune.spaces.decode_engine(max_lanes, max_seq_len),
        score_fn=score, default=None)

register_env("MXNET_GEN_PAGE_SIZE", 16, int,
             "KV-pool page size (tokens per page) for DecodeEngine.")
register_env("MXNET_GEN_NUM_PAGES", 128, int,
             "KV-pool page count (page 0 is reserved scratch) for "
             "DecodeEngine.")
register_env("MXNET_GEN_MAX_LANES", 8, int,
             "Largest decode lane-count bucket (max sequences advancing "
             "per decode step).")
register_env("MXNET_GEN_MAX_NEW_TOKENS", 64, int,
             "Default generation budget when a request does not say.")
register_env("MXNET_GEN_PENDING_QUEUE", 256, int,
             "Bounded admission queue for DecodeEngine.submit; beyond it "
             "submissions raise QueueFullError (HTTP 429).")

_DONE = object()  # GenStream queue sentinel


class GenStream:
    """One request's streaming handle: iterate tokens as they decode.

    ``for tok in stream`` yields generated token ids incrementally;
    :meth:`result` blocks for the full list.  ``ttft_ms`` / ``itl_ms``
    expose this request's observed first-token latency and inter-token
    gaps once available."""

    def __init__(self, prompt, max_new_tokens):
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.ttft_ms: Optional[float] = None
        self.itl_ms: List[float] = []
        self._t0 = time.monotonic()
        self._t_last = None
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._exc: Optional[BaseException] = None

    # -- engine side ------------------------------------------------------
    def _emit(self, token: int) -> float:
        """Record one generated token; returns the gap (ms) it observed
        (TTFT for the first token, ITL after)."""
        now = time.monotonic()
        if self._t_last is None:
            gap = (now - self._t0) * 1e3
            self.ttft_ms = gap
        else:
            gap = (now - self._t_last) * 1e3
            self.itl_ms.append(gap)
        self._t_last = now
        self.tokens.append(int(token))
        self._q.put(int(token))
        return gap

    def _finish(self, exc: Optional[BaseException] = None):
        if self._done.is_set():
            return
        self._exc = exc
        self._done.set()
        self._q.put(_DONE)

    # -- consumer side ----------------------------------------------------
    @property
    def done(self) -> bool:
        return self._done.is_set()

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _DONE:
                if self._exc is not None:
                    raise self._exc
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation still running")
        if self._exc is not None:
            raise self._exc
        return list(self.tokens)


class _Seq:
    """Engine-internal live-sequence state (one decode lane's occupant)."""

    __slots__ = ("sid", "stream", "tokens", "gen_count", "max_new",
                 "deadline", "eos_id", "admitted_at")

    def __init__(self, sid, stream, deadline, eos_id):
        self.sid = sid
        self.stream = stream
        self.tokens = list(stream.prompt)  # prompt + generated so far
        self.gen_count = len(stream.tokens)
        self.max_new = stream.max_new_tokens
        self.deadline = deadline  # absolute monotonic seconds or None
        self.eos_id = eos_id
        self.admitted_at = 0.0


class _GenMetrics:
    """Telemetry collector for one engine: token throughput, TTFT/ITL
    histograms, admission/retire/preempt counters, lane occupancy."""

    def __init__(self):
        reg = self._registry = _telemetry.Registry()
        self.tokens = reg.counter("mxtpu_gen_tokens_total")
        self.admitted = reg.counter("mxtpu_gen_sequences_admitted_total")
        self.retired = reg.counter("mxtpu_gen_sequences_retired_total")
        self.preempted = reg.counter("mxtpu_gen_sequences_preempted_total")
        self.expired = reg.counter("mxtpu_gen_sequences_expired_total")
        self.rejected = reg.counter("mxtpu_gen_sequences_rejected_total")
        self.failed = reg.counter("mxtpu_gen_sequences_failed_total")
        self.steps = reg.counter("mxtpu_gen_decode_steps_total")
        self.cold_steps = reg.counter("mxtpu_gen_decode_cold_steps_total")
        # 0.5ms .. ~16s exponential buckets
        self.ttft = reg.histogram("mxtpu_gen_ttft_ms")
        self.itl = reg.histogram("mxtpu_gen_itl_ms")
        self.g_active = reg.gauge("mxtpu_gen_active_lanes")
        self.g_pending = reg.gauge("mxtpu_gen_pending_requests")
        _telemetry.register_collector(self)

    def render_prometheus(self):
        return self._registry.render_prometheus()


class DecodeEngine:
    """Continuous-batching generation over a decoder-only LM checkpoint.

    Parameters
    ----------
    params : dict | str
        ``{name: array}`` (``arg:`` prefixes allowed) or a ``.params``
        path — the ``get_transformer_lm`` training checkpoint; all
        prefill/decode executors share one copy of the weights.
    vocab_size, num_layers, num_heads, hidden, max_seq_len
        Model geometry (must match the checkpoint).
    lane_buckets : sequence of int, optional
        Decode lane-count buckets (default ``pow2_buckets(
        MXNET_GEN_MAX_LANES)``); one executable per bucket.
    page_size, num_pages : int, optional
        KV-pool geometry (``MXNET_GEN_PAGE_SIZE`` / ``_NUM_PAGES``).
    prefill_len_buckets, prefill_batch_buckets
        Prompt-length and prefill-batch shape quantization; one
        :class:`BucketedPredictor` per length bucket.
    eos_id : int, optional
        Token id that ends a sequence early.
    """

    def __init__(self, params, vocab_size, num_layers=4, num_heads=8,
                 hidden=512, max_seq_len=128,
                 lane_buckets: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_len_buckets: Optional[Sequence[int]] = None,
                 prefill_batch_buckets: Sequence[int] = (1, 2, 4),
                 eos_id: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 ctx=None, dtype=np.float32, warmup: bool = True,
                 start: bool = True):
        from .. import ndarray as nd
        from ..models.transformer import (get_transformer_lm_decode,
                                          get_transformer_lm_prefill)
        from ..predictor import Predictor

        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.hidden = int(hidden)
        self.max_seq_len = int(max_seq_len)
        self.head_dim = self.hidden // self.num_heads
        self.eos_id = eos_id
        self._ctx = ctx
        self._dtype = np.dtype(dtype)
        # unset knobs consult the autotuner before the env defaults:
        # explicit constructor args always pin, tuned winners beat the
        # built-in defaults, env vars remain the no-autotune fallback
        tuned = None
        if page_size is None or lane_buckets is None:
            tuned = _autotune_engine_config(
                self.num_layers, self.num_heads, self.head_dim,
                self.max_seq_len, self._dtype,
                max_lanes=(max(int(b) for b in lane_buckets)
                           if lane_buckets is not None
                           else env("MXNET_GEN_MAX_LANES", 8, int)))
        if page_size is None and tuned:
            page_size = tuned.get("page_size")
        if lane_buckets is None and tuned:
            lane_buckets = tuned.get("lane_buckets")
        self.page_size = int(env("MXNET_GEN_PAGE_SIZE", 16, int)
                             if page_size is None else page_size)
        self.num_pages = int(env("MXNET_GEN_NUM_PAGES", 128, int)
                             if num_pages is None else num_pages)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        self.lane_buckets = tuple(sorted(set(
            int(b) for b in (lane_buckets if lane_buckets is not None
                             else pow2_buckets(
                                 env("MXNET_GEN_MAX_LANES", 8, int))))))
        self.max_lanes = self.lane_buckets[-1]
        if prefill_len_buckets is None:
            prefill_len_buckets = [b for b in pow2_buckets(self.max_seq_len)
                                   if b >= min(8, self.max_seq_len)]
        self.prefill_len_buckets = tuple(sorted(set(
            int(b) for b in prefill_len_buckets)))
        self.prefill_batch_buckets = tuple(sorted(set(
            int(b) for b in prefill_batch_buckets)))
        self.max_pending = int(env("MXNET_GEN_PENDING_QUEUE", 256, int)
                               if max_pending is None else max_pending)
        self.default_max_new = env("MXNET_GEN_MAX_NEW_TOKENS", 64, int)

        if isinstance(params, str):
            params = nd.load(params)
        # one shared copy of the weights: Predictor passes live NDArrays
        # through rebinds, so every bucket executor binds the same arrays
        self._params = dict(params)

        self.pool = PagedKVPool(self.num_pages, self.page_size,
                                self.num_layers, self.num_heads,
                                self.head_dim, dtype=self._dtype)
        self.metrics = _GenMetrics()

        # prefill: one BucketedPredictor per prompt-length bucket.
        # Symbols build under a fresh NameManager so auto-generated op
        # names — and with them symbol.tojson(), the compile-cache graph
        # fingerprint — are independent of process construction history:
        # an engine restored from an AOT bundle must re-derive the same
        # digests the bundle was saved under.
        from ..name import NameManager

        self._prefill: Dict[int, BucketedPredictor] = {}
        for L in self.prefill_len_buckets:
            with NameManager():
                symbol = get_transformer_lm_prefill(
                    self.vocab_size, self.num_layers, self.num_heads,
                    self.hidden, seq_len=L, max_seq_len=self.max_seq_len)
            bp = BucketedPredictor(symbol, self._params, {"data": (L,)},
                                   self.prefill_batch_buckets, ctx=ctx,
                                   dtype=dtype)
            for pred in bp._preds.values():
                pred._exec._cache_kind = "gen-prefill"
            self._prefill[L] = bp

        # decode: one fixed-lane Predictor per lane bucket (shared weights
        # via reshape; pool shapes are lane-independent)
        with NameManager():
            dec_symbol = get_transformer_lm_decode(
                self.vocab_size, self.num_layers, self.num_heads,
                self.hidden, max_seq_len=self.max_seq_len,
                lanes=self.max_lanes, num_pages=self.num_pages,
                page_size=self.page_size, max_pages=self.max_pages)
        pool_shape = (self.num_pages, self.page_size, self.num_heads,
                      self.head_dim)
        shapes = {"data": (self.max_lanes,),
                  "positions": (self.max_lanes,),
                  "page_table": (self.max_lanes, self.max_pages)}
        for i in range(self.num_layers):
            shapes["layer%d_k_pool" % i] = pool_shape
            shapes["layer%d_v_pool" % i] = pool_shape
        base = Predictor(dec_symbol, self._params, shapes, ctx=ctx,
                         dtype=dtype)
        self._decode: Dict[int, Predictor] = {self.max_lanes: base}
        for b in self.lane_buckets[:-1]:
            self._decode[b] = base.reshape(
                {"data": (b,), "positions": (b,), "page_table": (b,
                 self.max_pages)})
        for pred in self._decode.values():
            pred._exec._cache_kind = "gen-step"

        # recompile-detector bookkeeping: lane buckets warmup compiled,
        # post-warmup steps that hit a novel (never-warmed) bucket
        self.warmed_lane_buckets = set()
        self._warned_lane_buckets = set()
        self.decode_cold_runs = 0

        self._cv = threading.Condition()
        self._pending: deque = deque()  # _Seq, FIFO (preempted go front)
        self._active: List[_Seq] = []
        self._sid = 0
        self._closed = False
        self._drain = True
        self._loop_thread = threading.Thread(
            target=self._loop, name="mxtpu-gen-engine", daemon=True)
        self._started = False
        if warmup:
            self.warmup()
        if start:
            self.start()

    # -- construction helpers ---------------------------------------------
    def spec(self) -> Dict:
        """Model/engine geometry needed to rebuild this engine against a
        new checkpoint (hot-swap, AOT warmup manifests, shadow replicas)."""
        return {
            "vocab_size": self.vocab_size, "num_layers": self.num_layers,
            "num_heads": self.num_heads, "hidden": self.hidden,
            "max_seq_len": self.max_seq_len,
            "lane_buckets": list(self.lane_buckets),
            "page_size": self.page_size, "num_pages": self.num_pages,
            "prefill_len_buckets": list(self.prefill_len_buckets),
            "prefill_batch_buckets": list(self.prefill_batch_buckets),
            "eos_id": self.eos_id, "max_pending": self.max_pending,
        }

    @classmethod
    def from_checkpoint(cls, prefix, epoch, **spec):
        """Build from ``save_checkpoint`` files; ``spec`` as for the
        constructor (see :meth:`spec`)."""
        return cls("%s-%04d.params" % (prefix, int(epoch)), **spec)

    def warmup(self):
        """Pre-compile every prefill (length x batch) bucket and every
        decode lane bucket, priming through the compile cache when it is
        enabled — post-warmup steady state performs ZERO XLA compiles,
        and an attached AOT bundle makes warmup deserialize-only."""
        for bp in self._prefill.values():
            bp.warmup()
        pool_shape = (self.num_pages, self.page_size, self.num_heads,
                      self.head_dim)
        zero_pool = np.zeros(pool_shape, self._dtype)
        for b in self.lane_buckets:
            pred = self._decode[b]
            pred.set_input("data", np.zeros((b,), self._dtype))
            pred.set_input("positions", np.zeros((b,), self._dtype))
            pred.set_input("page_table",
                           np.zeros((b, self.max_pages), self._dtype))
            for i in range(self.num_layers):
                pred.set_input("layer%d_k_pool" % i, zero_pool)
                pred.set_input("layer%d_v_pool" % i, zero_pool)
            pred._exec.forward(is_train=False)
            for out in pred.get_outputs():
                out.asnumpy()  # block until compiled + ran
            self.warmed_lane_buckets.add(b)
        return self

    def compiled_entries(self):
        """Primed compile-cache wrappers across prefill and decode
        executors (kinds ``gen-prefill`` / ``gen-step``) — the input to
        ``checkpoint.save_aot_bundle`` so an autoscaled replica serves
        its first generate request with zero cold compiles."""
        from ..compile_cache import CachedFunction

        out = []
        for bp in self._prefill.values():
            out.extend(bp.compiled_entries())
        for pred in self._decode.values():
            for fn in pred._exec._jit_cache.values():
                if isinstance(fn, CachedFunction):
                    out.append(fn)
        return out

    def cold_decode_runs(self) -> int:
        """Post-warmup decode steps that hit a never-warmed lane bucket
        plus cold prefill flushes — 0 is the "steady state never
        recompiles" acceptance check."""
        return self.decode_cold_runs + sum(bp.cold_runs
                                           for bp in self._prefill.values())

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if not self._started:
            self._started = True
            self._loop_thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Stop the engine.  With ``drain`` (default) queued and active
        sequences finish first (bounded by ``timeout`` seconds), without
        it they fail fast with :class:`ServerClosedError`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._drain = drain
            if not drain:
                self._fail_all_locked(ServerClosedError(
                    "engine stopped before completion"))
            self._cv.notify_all()
        if self._started:
            self._loop_thread.join(timeout)
        with self._cv:
            # drain deadline expired with work outstanding (or fail-fast
            # stop racing the loop): cancel whatever is left
            self._fail_all_locked(ServerClosedError("engine stopped"))

    def handoff(self) -> int:
        """Preempt every queued and active stream WITHOUT stopping the
        engine: each fails with :class:`ServerClosedError`, which a
        router-level consumer treats as a replica failure and re-submits
        (prompt + emitted tokens) on a surviving replica — greedy decode
        makes the resumed transcript bit-identical.  The graceful
        page-out handoff: call this before the owning server releases
        its device memory.  Returns the number of streams handed off."""
        with self._cv:
            n = len(self._pending) + len(self._active)
            self._fail_all_locked(ServerClosedError(
                "replica preempted: stream handed off"))
            self._cv.notify_all()
        if n:
            _telemetry.log_event("gen_handoff", streams=n)
        return n

    def _fail_all_locked(self, exc):
        n = 0
        for seq in list(self._pending) + list(self._active):
            self.pool.free(seq.sid)
            seq.stream._finish(exc)
            n += 1
        self._pending.clear()
        del self._active[:]
        if n:
            self.metrics.failed.inc(n)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)

    # -- request path ------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenStream:
        """Queue one prompt for generation; returns its
        :class:`GenStream`.  Raises :class:`QueueFullError` when the
        pending queue is at capacity (HTTP 429 — retry with backoff) and
        :class:`MXNetError` for prompts that can never fit."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise MXNetError("empty prompt")
        max_new = int(self.default_max_new if max_new_tokens is None
                      else max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new
        if total > self.max_seq_len:
            raise MXNetError(
                "prompt (%d) + max_new_tokens (%d) exceeds max_seq_len %d"
                % (len(prompt), max_new, self.max_seq_len))
        if self.pool.pages_for(total) > self.pool.capacity:
            raise MXNetError(
                "request needs %d KV pages but the pool only has %d — it "
                "can never be admitted" %
                (self.pool.pages_for(total), self.pool.capacity))
        stream = GenStream(prompt, max_new)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        with self._cv:
            if self._closed:
                raise ServerClosedError("engine is stopped")
            if len(self._pending) >= self.max_pending:
                self.metrics.rejected.inc()
                raise QueueFullError(
                    "generation queue full (%d pending); retry with "
                    "backoff" % len(self._pending))
            self._pending.append(_Seq(self._sid, stream, deadline,
                                      self.eos_id))
            self._sid += 1
            self.metrics.g_pending.set(len(self._pending))
            self._cv.notify_all()
        return stream

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None,
                 timeout: Optional[float] = 300.0) -> List[int]:
        """Blocking convenience wrapper: the full generated token list."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    def pending_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def active_lanes(self) -> int:
        with self._cv:
            return len(self._active)

    def snapshot(self) -> dict:
        with self._cv:
            return {"pending": len(self._pending),
                    "active": len(self._active),
                    "tokens_total": self.metrics.tokens.value,
                    "cold_decode_runs": self.cold_decode_runs(),
                    "kv": self.pool.snapshot()}

    # -- engine loop -------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._active \
                        and not self._closed:
                    self._cv.wait(0.05)
                if self._closed and not self._active and \
                        (not self._pending or not self._drain):
                    for seq in self._pending:
                        seq.stream._finish(ServerClosedError(
                            "engine stopped before execution"))
                    self._pending.clear()
                    return
            try:
                self._admit()
                if self._active:
                    self._decode_step()
            except BaseException as exc:  # fault-injected or real: contain
                logging.warning("generation engine step failed: %r", exc)
                with self._cv:
                    self._fail_all_locked(exc)
                _telemetry.log_event("gen_engine_error", error=repr(exc))

    def _prefill_bucket_for(self, n: int) -> int:
        for L in self.prefill_len_buckets:
            if L >= n:
                return L
        raise MXNetError("prompt of %d exceeds largest prefill bucket %d"
                         % (n, self.prefill_len_buckets[-1]))

    def _admit(self):
        """Move pending sequences into free decode lanes: allocate KV
        pages, run bucketed prefill, stream each sequence's first token."""
        batch: List[_Seq] = []
        now = time.monotonic()
        free_pages = self.pool.free_pages()
        with self._cv:
            while self._pending and \
                    len(self._active) + len(batch) < self.max_lanes:
                seq = self._pending[0]
                if seq.deadline is not None and now > seq.deadline:
                    self._pending.popleft()
                    self.metrics.expired.inc()
                    seq.stream._finish(DeadlineExceededError(
                        "request waited past its TTFT deadline"))
                    continue
                need = self.pool.pages_for(len(seq.tokens))
                if need > free_pages:
                    break  # wait for active lanes to retire/free pages
                free_pages -= need
                self._pending.popleft()
                batch.append(seq)
            self.metrics.g_pending.set(len(self._pending))
        if not batch:
            return
        faults.fire("generation.engine.admit")
        # group by prompt-length bucket, chunk to the prefill batch cap
        by_bucket: Dict[int, List[_Seq]] = {}
        for seq in batch:
            by_bucket.setdefault(
                self._prefill_bucket_for(len(seq.tokens)), []).append(seq)
        for L, seqs in sorted(by_bucket.items()):
            bp = self._prefill[L]
            cap = bp.max_batch_size
            for ofs in range(0, len(seqs), cap):
                self._prefill_group(L, seqs[ofs:ofs + cap])

    def _prefill_group(self, L: int, seqs: List[_Seq]):
        bp = self._prefill[L]
        items = []
        admitted = []
        for seq in seqs:
            try:
                self.pool.alloc(seq.sid, len(seq.tokens))
            except KVPoolExhaustedError:
                # admission raced a concurrent consumer: wait a round
                with self._cv:
                    self._pending.appendleft(seq)
                continue
            buf = np.zeros((L,), self._dtype)
            buf[:len(seq.tokens)] = seq.tokens
            items.append({"data": buf})
            admitted.append(seq)
        seqs = admitted
        if not seqs:
            return
        _, results = bp.forward_batch(items)
        now_active = []
        for seq, outs in zip(seqs, results):
            n = len(seq.tokens)
            logits = outs[0]  # (L, vocab)
            for layer in range(self.num_layers):
                self.pool.write_prefill(seq.sid, layer,
                                        outs[1 + 2 * layer],
                                        outs[2 + 2 * layer], n)
            tok = int(np.argmax(logits[n - 1]))
            self._emit(seq, tok)
            seq.admitted_at = time.monotonic()
            now_active.append(seq)
        with self._cv:
            self._active.extend(s for s in now_active
                                if not s.stream.done)
            self.metrics.admitted.inc(len(now_active))
            self.metrics.g_active.set(len(self._active))

    def _emit(self, seq: _Seq, tok: int):
        """Stream one generated token; retires the sequence when it hit
        its budget or EOS.  Returns True when the sequence retired."""
        first = not seq.stream.tokens
        gap = seq.stream._emit(tok)
        seq.tokens.append(tok)
        seq.gen_count += 1
        self.metrics.tokens.inc()
        (self.metrics.ttft if first else self.metrics.itl).observe(gap)
        if seq.gen_count >= seq.max_new or \
                (seq.eos_id is not None and tok == seq.eos_id):
            self._retire(seq)
            return True
        return False

    def _retire(self, seq: _Seq):
        faults.fire("generation.engine.retire")
        self.pool.free(seq.sid)
        seq.stream._finish(None)
        self.metrics.retired.inc()

    def _preempt_one(self, exclude: Optional[_Seq] = None) -> bool:
        """Free the youngest active lane's pages and push the sequence
        back to the FRONT of the pending queue for re-prefill of
        prompt + generated-so-far (greedy decode is deterministic, so
        its stream continues without a hiccup)."""
        with self._cv:
            victims = [s for s in self._active if s is not exclude]
            if not victims:
                victims = [s for s in self._active]
            if not victims:
                return False
            victim = max(victims, key=lambda s: s.admitted_at)
            self._active.remove(victim)
            self._pending.appendleft(victim)
            self.metrics.g_active.set(len(self._active))
            self.metrics.g_pending.set(len(self._pending))
        self.pool.free(victim.sid)
        self.metrics.preempted.inc()
        _telemetry.log_event("gen_preempt", sid=victim.sid,
                             tokens=len(victim.tokens))
        return True

    def _lane_bucket_for(self, n: int) -> int:
        for b in self.lane_buckets:
            if b >= n:
                return b
        raise MXNetError("%d active lanes exceed largest bucket %d"
                         % (n, self.lane_buckets[-1]))

    def _decode_step(self):
        """One continuous-batching iteration: every active lane advances
        one token through the fixed-shape paged-attention executable."""
        faults.fire("generation.engine.step")
        # grow each lane's KV allocation for the token about to be
        # written; pool exhaustion preempts the youngest other lane
        for seq in list(self._active):
            # an earlier lane's extend may have preempted this one already
            while seq in self._active:
                try:
                    self.pool.extend(seq.sid, len(seq.tokens))
                    break
                except KVPoolExhaustedError:
                    if not self._preempt_one(exclude=seq):
                        raise
        active = list(self._active)
        if not active:
            return
        b = self._lane_bucket_for(len(active))
        if b not in self.warmed_lane_buckets:
            self.decode_cold_runs += 1
            self.metrics.cold_steps.inc()
            self.warmed_lane_buckets.add(b)
            if b not in self._warned_lane_buckets:
                self._warned_lane_buckets.add(b)
                logging.warning(
                    "generation: decode step hit never-warmed lane bucket "
                    "%d post-warmup (fresh XLA compile on the serving "
                    "path) — add it to lane_buckets/warmup", b)
                _telemetry.log_event("gen_decode_cold_bucket", lanes=b)
        pred = self._decode[b]
        data = np.zeros((b,), self._dtype)
        positions = np.zeros((b,), self._dtype)
        table = np.zeros((b, self.max_pages), self._dtype)
        for i, seq in enumerate(active):
            data[i] = seq.tokens[-1]
            positions[i] = len(seq.tokens) - 1  # slot the new K/V lands in
            table[i] = self.pool.page_table_row(seq.sid, self.max_pages)
        pred.set_input("data", data)
        pred.set_input("positions", positions)
        pred.set_input("page_table", table)
        for i in range(self.num_layers):
            pred.set_input("layer%d_k_pool" % i, self.pool.k_pools[i])
            pred.set_input("layer%d_v_pool" % i, self.pool.v_pools[i])
        pred._exec.forward(is_train=False)
        outs = [o.asnumpy() for o in pred.get_outputs()]
        logits = outs[0]
        for i in range(self.num_layers):
            np.copyto(self.pool.k_pools[i], outs[1 + 2 * i])
            np.copyto(self.pool.v_pools[i], outs[2 + 2 * i])
        self.metrics.steps.inc()
        retired = []
        for i, seq in enumerate(active):
            if self._emit(seq, int(np.argmax(logits[i]))):
                retired.append(seq)
        if retired:
            with self._cv:
                for seq in retired:
                    if seq in self._active:
                        self._active.remove(seq)
                self.metrics.g_active.set(len(self._active))
                self._cv.notify_all()

"""Placing and collecting parameter dicts against a named mesh.

``shard_params`` / ``gather_params`` are the SNIPPETS.md [3] helpers over
this framework's name->NDArray dicts: place once (committed
``NamedSharding``s, so every jitted step is partitioned from its inputs),
collect without assuming single-host addressability, and account bytes so
the memory win of a layout is a number (telemetry gauges, shard_probe),
not a feeling.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["make_shardings", "shard_params", "gather_params",
           "validate_specs", "spec_shard_factor", "param_bytes"]


def _nd():
    from .. import ndarray as nd

    return nd


def make_shardings(mesh, specs: Dict[str, object]) -> Dict[str, object]:
    """{name: PartitionSpec} -> {name: NamedSharding} on ``mesh``."""
    from jax.sharding import NamedSharding

    return {name: NamedSharding(mesh, spec) for name, spec in specs.items()}


def spec_shard_factor(mesh, spec) -> int:
    """How many ways a spec splits an array (product of its mesh axis
    sizes) — the per-device memory divisor."""
    factor = 1
    for entry in tuple(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for ax in axes:
            factor *= int(mesh.shape[ax])
    return factor


def validate_specs(mesh, specs: Dict[str, object],
                   shapes: Dict[str, Tuple[int, ...]]) -> None:
    """Reject specs whose sharded dims don't divide evenly by their mesh
    axes.  GSPMD would pad uneven shards silently; an uneven split of a
    weight is almost always a mis-written rule, so fail loudly with the
    parameter name (MXNET_SHARDING_VALIDATE=0 to allow padding)."""
    problems = []
    for name, spec in specs.items():
        shape = tuple(shapes.get(name, ()))
        for dim, entry in enumerate(tuple(spec)):
            if entry is None or dim >= len(shape):
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            factor = 1
            for ax in axes:
                if ax not in mesh.shape:
                    problems.append("%s: spec axis %r is not a mesh axis %s"
                                    % (name, ax, tuple(mesh.axis_names)))
                    factor = 0
                    break
                factor *= int(mesh.shape[ax])
            if factor and shape[dim] % factor != 0:
                problems.append(
                    "%s: dim %d (size %d) not divisible by the %d-way %r "
                    "split" % (name, dim, shape[dim], factor, entry))
    if problems:
        raise MXNetError("invalid partition specs for mesh %s:\n  %s"
                         % (dict((a, int(mesh.shape[a]))
                                 for a in mesh.axis_names),
                            "\n  ".join(problems)))


def _already_placed(x, target) -> bool:
    """True when ``x`` is a committed jax array whose sharding is already
    equivalent to ``target`` — re-placement would be a pointless copy on a
    single host and an ERROR for cross-process arrays (whose shards cannot
    be rebuilt from one host's view)."""
    sharding = getattr(x, "sharding", None)
    if sharding is None or not getattr(x, "committed", True):
        return False
    try:
        return sharding.is_equivalent_to(target, x.ndim)
    except Exception:
        return sharding == target


def place(x, mesh, spec):
    """Place one array (jax array / NDArray / numpy) onto the mesh under
    ``spec``.  Already-correctly-placed arrays pass through untouched;
    cross-process arrays that would need a true reshard raise (gather on
    the caller first)."""
    import jax
    from jax.sharding import NamedSharding

    nd = _nd()
    if isinstance(x, nd.NDArray):
        x = x._data
    target = NamedSharding(mesh, spec)
    if _already_placed(x, target):
        return x
    if not getattr(x, "is_fully_addressable", True):
        if getattr(x, "is_fully_replicated", False):
            x = np.asarray(x.addressable_shards[0].data)
        else:
            raise MXNetError(
                "cannot re-place a cross-process sharded array (sharding %s "
                "-> %s): gather it first or restore it directly onto the "
                "target mesh" % (getattr(x, "sharding", None), target))
    if jax.process_count() > 1:
        host = np.asarray(x)
        return jax.make_array_from_callback(host.shape, target,
                                            lambda idx: host[idx])
    return jax.device_put(x, target)


def shard_params(params: Dict[str, object], mesh,
                 specs: Optional[Dict[str, object]] = None,
                 validate: bool = True) -> Dict[str, object]:
    """Place a {name: NDArray} dict against ``mesh`` under ``specs``
    ({name: PartitionSpec}; missing names replicate).  Returns a new dict
    of NDArrays backed by committed mesh-placed arrays."""
    from jax.sharding import PartitionSpec

    nd = _nd()
    specs = specs or {}
    if validate:
        validate_specs(mesh, {k: specs.get(k, PartitionSpec())
                              for k in params},
                       {k: tuple(getattr(v, "shape", ()))
                        for k, v in params.items()})
    out = {}
    for name, arr in params.items():
        placed = place(arr, mesh, specs.get(name, PartitionSpec()))
        out[name] = arr if isinstance(arr, nd.NDArray) and \
            placed is arr._data else nd.NDArray(placed)
    return out


def gather_params(params: Dict[str, object]) -> Dict[str, object]:
    """Collect a (possibly sharded) {name: NDArray} dict to host numpy.

    Single-host shards concatenate locally; cross-process arrays gather
    through ``multihost_utils.process_allgather`` so every process gets
    the full value (the explicit inverse of :func:`shard_params` — NOT on
    any hot path)."""
    nd = _nd()
    out = {}
    for name, arr in params.items():
        x = arr._data if isinstance(arr, nd.NDArray) else arr
        if getattr(x, "is_fully_addressable", True):
            out[name] = np.asarray(x)
        elif getattr(x, "is_fully_replicated", False):
            out[name] = np.asarray(x.addressable_shards[0].data)
        else:
            from jax.experimental import multihost_utils

            out[name] = np.asarray(multihost_utils.process_allgather(
                x, tiled=True))
    return out


def param_bytes(arrays) -> Tuple[int, int]:
    """(per_device_bytes, replicated_bytes) for an iterable of arrays.

    ``replicated_bytes`` is what one device would hold if everything were
    fully replicated (the pre-sharding layout); ``per_device_bytes`` is
    the average actual residency per device under the current placement —
    the telemetry gauge pair that makes a tensor-parallel memory win
    visible in BENCH records."""
    nd = _nd()
    per_device = 0.0
    replicated = 0
    for arr in arrays:
        if arr is None:
            continue
        x = arr._data if isinstance(arr, nd.NDArray) else arr
        nbytes = int(np.prod(x.shape)) * x.dtype.itemsize
        replicated += nbytes
        sharding = getattr(x, "sharding", None)
        ndev = len(sharding.device_set) if sharding is not None else 1
        shards = getattr(x, "addressable_shards", None)
        if shards and len(sharding.addressable_devices) == ndev:
            per_device += sum(int(np.prod(s.data.shape))
                              * s.data.dtype.itemsize
                              for s in shards) / ndev
        else:
            # non-addressable (multi-host): derive from the spec instead
            spec = getattr(sharding, "spec", None)
            factor = spec_shard_factor(sharding.mesh, spec) \
                if spec is not None else 1
            per_device += nbytes / factor
    return int(per_device), replicated

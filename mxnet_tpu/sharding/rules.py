"""Regex partition rules: dotted/underscored parameter names ->
``PartitionSpec``.

The SNIPPETS.md [2] ``match_partition_rules`` pattern, grown into the
framework's single source of layout truth: an ORDERED list of
``(regex, PartitionSpec)`` pairs is matched (``re.search``) against each
parameter name; the first hit wins.  Scalars and single-element arrays
short-circuit to replicated (there is nothing to split), and every
resolution is explainable — :meth:`PartitionRules.explain` reports which
rule claimed each parameter, so a layout regression is a diffable table
(tools/shard_probe.py) instead of an OOM three hours into a run.

Presets encode the bench-model layouts:

* ``replicated`` — pure data parallelism, every parameter on every device
  (exactly the pre-sharding executor_group behavior, now as data);
* ``transformer_megatron`` — Megatron-style tensor parallelism for the
  ``models.transformer`` LM family: attention qkv / MLP fc1 split by
  output rows (column-parallel), proj / fc2 split by input columns
  (row-parallel), vocab-parallel lm_head, norms replicated.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["PartitionRules", "as_rules", "match_partition_rules",
           "explain_partition_rules", "get_preset", "PRESETS"]


def _pspec():
    from jax.sharding import PartitionSpec

    return PartitionSpec


def _leaf_shape(leaf):
    """Shape of a rule-matching leaf: array-likes expose .shape; tuples/
    lists of ints are taken as shapes directly (so rules resolve from
    ``infer_shape`` output before any array exists)."""
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return tuple(shape)
    if isinstance(leaf, (tuple, list)) and \
            all(isinstance(d, (int, np.integer)) for d in leaf):
        return tuple(int(d) for d in leaf)
    raise MXNetError(
        "cannot derive a shape for partition-rule matching from %r" % (leaf,))


class PartitionRules:
    """Ordered (regex, PartitionSpec) rules with an optional replicated
    fallback.

    ``fallback``: a PartitionSpec used when no rule matches (pass
    ``PartitionSpec()`` for replicate-unmatched); ``None`` makes an
    unmatched parameter a hard error naming the parameter — the safe
    default for hand-written rule sets, where a typo silently replicating
    a 10 GB embedding is the failure mode to catch.
    """

    def __init__(self, rules: Sequence[Tuple[str, object]], fallback=None,
                 name: str = "custom"):
        self.name = name
        self.fallback = fallback
        self.rules = []
        for pattern, spec in rules:
            try:
                self.rules.append((re.compile(pattern), spec))
            except re.error as e:
                raise MXNetError(
                    "bad partition-rule regex %r: %s" % (pattern, e))

    # ------------------------------------------------------------------
    def spec_for(self, param_name: str, shape) -> object:
        """Resolve one name (+shape, for the scalar short-circuit)."""
        spec, _ = self._resolve(param_name, shape)
        return spec

    def _resolve(self, param_name, shape):
        P = _pspec()
        shape = _leaf_shape(shape)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P(), "<scalar>"
        for regex, spec in self.rules:
            if regex.search(param_name) is not None:
                return spec, regex.pattern
        if self.fallback is not None:
            return self.fallback, "<fallback>"
        raise MXNetError(
            "no partition rule matches parameter %r (shape %s); add a rule "
            "or a replicated fallback (fallback=PartitionSpec())"
            % (param_name, shape))

    def match(self, params: Dict[str, object]) -> Dict[str, object]:
        """{name: array-or-shape} -> {name: PartitionSpec}."""
        return {name: self._resolve(name, leaf)[0]
                for name, leaf in params.items()}

    def explain(self, params: Dict[str, object]) -> List[dict]:
        """Per-parameter resolution report: which rule claimed each name.

        Rows: {"param", "shape", "rule", "spec"} where ``rule`` is the
        matching regex pattern, ``<scalar>`` (short-circuit), or
        ``<fallback>``.
        """
        rows = []
        for name, leaf in params.items():
            spec, rule = self._resolve(name, leaf)
            rows.append({"param": name, "shape": _leaf_shape(leaf),
                         "rule": rule, "spec": tuple(spec)})
        return rows

    def explain_str(self, params: Dict[str, object]) -> str:
        rows = self.explain(params)
        w = max([len(r["param"]) for r in rows] + [5])
        lines = ["%-*s  %-18s  %-24s  %s" % (w, "param", "shape", "spec",
                                             "rule")]
        for r in rows:
            lines.append("%-*s  %-18s  %-24s  %s" % (
                w, r["param"], r["shape"], r["spec"], r["rule"]))
        return "\n".join(lines)

    def __repr__(self):
        return "PartitionRules(%s, %d rules, fallback=%s)" % (
            self.name, len(self.rules), self.fallback)


def as_rules(rules, fallback="unset") -> "PartitionRules":
    """Coerce any accepted rule form: a preset name, a PartitionRules, or
    a raw ``[(regex, spec), ...]`` list (fallback defaults to None for raw
    lists — unmatched raises)."""
    if isinstance(rules, PartitionRules):
        return rules
    if isinstance(rules, str):
        return get_preset(rules)
    return PartitionRules(rules,
                          fallback=None if fallback == "unset" else fallback)


def match_partition_rules(rules, params, fallback="unset"):
    """Functional form (the SNIPPETS.md [2] surface): ordered
    ``(regex, PartitionSpec)`` rules over ``{name: array-or-shape}`` ->
    ``{name: PartitionSpec}``, scalars replicated, unmatched raising unless
    a ``fallback`` spec is given."""
    return as_rules(rules, fallback).match(params)


def explain_partition_rules(rules, params, fallback="unset"):
    """Like :func:`match_partition_rules` but returns the per-param
    explanation rows instead of bare specs."""
    return as_rules(rules, fallback).explain(params)


# ----------------------------------------------------------------------
# presets for the bench model families
# ----------------------------------------------------------------------
def _replicated() -> PartitionRules:
    P = _pspec()
    return PartitionRules([], fallback=P(), name="replicated")


def _resnet() -> PartitionRules:
    # ResNet-50 at bench scale fits every device: pure data parallelism,
    # parameters replicated, batch on the 'data' axis (the pre-sharding
    # executor_group layout expressed as rules)
    P = _pspec()
    return PartitionRules([], fallback=P(), name="resnet")


def _transformer_megatron() -> PartitionRules:
    # models/transformer.py naming: layerN_{qkv,proj,fc1,fc2}_{weight,bias},
    # tok_embed/pos_embed, lm_head, *_ln*/ln_f norms.  FullyConnected
    # weights are (out, in) — column-parallel shards rows (axis 0),
    # row-parallel shards columns (axis 1).  Row-parallel biases stay
    # replicated (added once after the partial-sum reduce).
    P = _pspec()
    return PartitionRules([
        (r"_(qkv|fc1)_weight$", P("model", None)),   # column parallel
        (r"_(qkv|fc1)_bias$", P("model")),
        (r"_(proj|fc2)_weight$", P(None, "model")),  # row parallel
        (r"_(proj|fc2)_bias$", P()),
        (r"tok_embed_weight$", P(None, "model")),    # hidden-dim split
        (r"pos_embed_weight$", P()),
        (r"lm_head_weight$", P("model", None)),      # vocab parallel
        (r"lm_head_bias$", P("model")),
        (r"(_ln\d*|ln_f)_(gamma|beta)$", P()),
        (r"_(gamma|beta)$", P()),                    # any other norm
    ], fallback=P(), name="transformer_megatron")


PRESETS = {
    "replicated": _replicated,
    "data_parallel": _replicated,
    "resnet": _resnet,
    "transformer_megatron": _transformer_megatron,
}


def get_preset(name: str) -> PartitionRules:
    try:
        return PRESETS[name]()
    except KeyError:
        raise MXNetError(
            "unknown partition-rule preset %r (have: %s)"
            % (name, ", ".join(sorted(PRESETS))))

"""Named-mesh construction — the device-layout half of the GSPMD story.

The executor stack expresses parallelism as data (`PartitionSpec`s over a
named mesh), so the mesh itself must be easy to build correctly: axis sizes
that multiply to the device count, one `-1` axis inferred from the rest,
and a device ordering that keeps the leading (usually "data") axis
contiguous per process so multi-host batches shard host-locally (the same
layout contract `jax.make_array_from_process_local_data` expects).

`build_mesh(("data", -1), ("model", 2))` on 8 devices -> a 4x2
`Mesh(..., ("data", "model"))`; on a v5e-64 pod the same call gives 32x2
without code changes — parallel layout is configuration, not code.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

from ..base import MXNetError

__all__ = ["MeshConfig", "build_mesh", "mesh_axes", "mesh_fingerprint"]

AxisSpec = Union[Tuple[str, int], Sequence]


class MeshConfig:
    """Declarative mesh layout: ordered (axis_name, size) pairs, at most one
    size of ``-1`` (inferred so the product covers every device).

    Accepts, for convenience at every call site (Module.bind kwargs, env
    vars, CLI tools):

    * ``MeshConfig(("data", -1), ("model", 2))``
    * ``MeshConfig.parse("data=-1,model=2")``
    * an existing ``jax.sharding.Mesh`` passes through :func:`build_mesh`.
    """

    def __init__(self, *axes: AxisSpec):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)) and axes[0] \
                and isinstance(axes[0][0], (list, tuple)):
            axes = tuple(axes[0])  # MeshConfig([("a", 1), ...]) form
        if not axes:
            raise MXNetError("MeshConfig needs at least one axis")
        names = []
        sizes = []
        for ax in axes:
            try:
                name, size = ax
            except (TypeError, ValueError):
                raise MXNetError(
                    "mesh axis must be a (name, size) pair, got %r" % (ax,))
            name = str(name)
            size = int(size)
            if size == 0 or size < -1:
                raise MXNetError(
                    "mesh axis %r size must be positive or -1 (inferred), "
                    "got %d" % (name, size))
            if name in names:
                raise MXNetError("duplicate mesh axis %r" % name)
            names.append(name)
            sizes.append(size)
        if sizes.count(-1) > 1:
            raise MXNetError(
                "at most one mesh axis may have size -1 (inferred), got %s"
                % list(zip(names, sizes)))
        self.names: Tuple[str, ...] = tuple(names)
        self.sizes: Tuple[int, ...] = tuple(sizes)

    @classmethod
    def parse(cls, text: str) -> "MeshConfig":
        """``"data=-1,model=2"`` -> MeshConfig (the env-var / CLI syntax)."""
        axes = []
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise MXNetError(
                    "mesh axis %r must be name=size (e.g. data=-1,model=2)"
                    % part)
            name, _, size = part.partition("=")
            try:
                axes.append((name.strip(), int(size)))
            except ValueError:
                raise MXNetError("mesh axis size %r is not an integer" % size)
        return cls(*axes)

    def resolve_sizes(self, num_devices: int) -> Tuple[int, ...]:
        """Concrete per-axis sizes for ``num_devices`` (fills the -1)."""
        fixed = 1
        for s in self.sizes:
            if s != -1:
                fixed *= s
        sizes = list(self.sizes)
        if -1 in sizes:
            if num_devices % fixed != 0:
                raise MXNetError(
                    "cannot infer mesh axis %r: %d devices not divisible by "
                    "the fixed axes %s" % (
                        self.names[sizes.index(-1)], num_devices,
                        {n: s for n, s in zip(self.names, self.sizes)
                         if s != -1}))
            sizes[sizes.index(-1)] = num_devices // fixed
        if int(np.prod(sizes)) != num_devices:
            raise MXNetError(
                "mesh %s covers %d devices but %d are available"
                % (dict(zip(self.names, sizes)), int(np.prod(sizes)),
                   num_devices))
        return tuple(sizes)

    def __repr__(self):
        return "MeshConfig(%s)" % ", ".join(
            "%s=%d" % (n, s) for n, s in zip(self.names, self.sizes))


def _as_config(axes) -> MeshConfig:
    if isinstance(axes, MeshConfig):
        return axes
    if isinstance(axes, str):
        return MeshConfig.parse(axes)
    if isinstance(axes, dict):
        return MeshConfig(*axes.items())
    return MeshConfig(*axes) if axes and isinstance(axes[0], (list, tuple)) \
        else MeshConfig(axes)


def build_mesh(axes="data=-1", devices=None):
    """Create a ``jax.sharding.Mesh`` with named axes over ``devices``
    (default: every device of every process).

    ``axes``: MeshConfig | "data=-1,model=2" | ((name, size), ...) | dict.
    Exactly one axis may be -1; its size is inferred.

    Process-aware layout: devices keep their ``jax.devices()`` order
    (grouped by process), and the LEADING axis must span whole processes —
    so a ``("data", ..., "model")`` mesh keeps each host's devices in one
    contiguous block of the data axis and model-axis collectives stay
    intra-host (ICI, not DCN).
    """
    import jax
    from jax.sharding import Mesh

    cfg = _as_config(axes)
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices, dtype=object).reshape(-1)
    sizes = cfg.resolve_sizes(devices.size)

    nproc = jax.process_count()
    if nproc > 1:
        per_proc = devices.size // nproc
        trailing = int(np.prod(sizes[1:])) if len(sizes) > 1 else 1
        if trailing > per_proc or per_proc % trailing != 0:
            raise MXNetError(
                "mesh %s: the non-leading axes (%d-way) must divide the "
                "per-process device count (%d) so the leading %r axis "
                "spans whole processes" % (
                    dict(zip(cfg.names, sizes)), trailing, per_proc,
                    cfg.names[0]))
    return Mesh(devices.reshape(sizes), cfg.names)


def mesh_axes(mesh) -> Dict[str, int]:
    """``{axis_name: size}`` for a Mesh (insertion-ordered)."""
    return {name: int(mesh.shape[name]) for name in mesh.axis_names}


def mesh_fingerprint(mesh) -> Tuple:
    """Stable, process-independent identity of a mesh's layout: ordered
    (axis, size) pairs plus the flattened device ids.  Unlike ``id(mesh)``
    this survives pickling boundaries, so it is what the persistent
    compile cache keys sharded executables by."""
    axes = tuple((str(n), int(mesh.shape[n])) for n in mesh.axis_names)
    dev_ids = tuple(int(d.id) for d in np.asarray(
        mesh.devices, dtype=object).reshape(-1))
    return (axes, dev_ids)

"""mxnet_tpu.sharding — named-mesh GSPMD partitioning for the Module /
executor stack.

ROADMAP item 1: multi-device training used to be data-parallel replication
on a hard-coded 1-D mesh inside executor_group.  This subsystem makes the
parallel layout DATA instead of code:

* :func:`build_mesh` — multi-axis named meshes (``("data", "model")``)
  from ``jax.devices()`` with ``-1`` axis inference and a process-aware
  device layout (mesh.py);
* :func:`match_partition_rules` / :class:`PartitionRules` — ordered regex
  rules over parameter names -> a ``PartitionSpec`` per parameter, with a
  replicated fallback, scalar short-circuit, explainable resolution, and
  presets for the bench models (rules.py);
* :func:`shard_params` / :func:`gather_params` — place or collect a param
  dict against the mesh through committed ``NamedSharding``s
  (placement.py).

The executor stack consumes these through ``Module.bind(..., mesh=...,
partition_rules=...)``: the fused train step is lowered ONCE under the
resulting shardings and XLA's SPMD partitioner inserts the collectives —
data-, tensor-, and (later) pipeline-parallelism become spec changes, not
code changes.  With no rules passed, nothing changes: the replicated
data-parallel path is bit-identical to before.

Env knobs (see docs/how_to/sharding.md):

* ``MXNET_SHARDING_MESH`` / ``MXNET_SHARDING_RULES`` activate a layout
  for any existing training script without code changes;
* ``MXNET_SHARDING_VALIDATE`` gates the uneven-split error;
* ``MXNET_SHARDING_EXPLAIN`` logs the resolved rule table at bind.
"""
from ..base import register_env

from .mesh import MeshConfig, build_mesh, mesh_axes, mesh_fingerprint
from .rules import (PartitionRules, PRESETS, as_rules,
                    explain_partition_rules, get_preset,
                    match_partition_rules)
from .placement import (gather_params, make_shardings, param_bytes, place,
                        shard_params, spec_shard_factor, validate_specs)

__all__ = [
    "MeshConfig", "build_mesh", "mesh_axes", "mesh_fingerprint",
    "PartitionRules", "PRESETS", "as_rules", "get_preset",
    "match_partition_rules", "explain_partition_rules",
    "shard_params", "gather_params", "make_shardings", "place",
    "param_bytes", "spec_shard_factor", "validate_specs",
]

register_env("MXNET_SHARDING_MESH", "", str,
             "Mesh layout ('data=-1,model=2') applied by Module.bind when "
             "no mesh argument is passed. Empty keeps the default "
             "replicated data-parallel layout.")
register_env("MXNET_SHARDING_RULES", "", str,
             "Partition-rule preset name (see sharding.PRESETS) applied by "
             "Module.bind when no partition_rules argument is passed. "
             "Requires a mesh (argument or MXNET_SHARDING_MESH).")
register_env("MXNET_SHARDING_VALIDATE", 1, int,
             "Reject PartitionSpecs whose sharded dims don't divide evenly "
             "by their mesh axes (GSPMD would silently pad). 0 allows "
             "uneven splits.")
register_env("MXNET_SHARDING_EXPLAIN", 0, int,
             "Log the resolved rule table (param -> rule -> spec) at bind "
             "time.")

"""Weight initializers.

TPU-native counterpart of /root/reference/python/mxnet/initializer.py.
API-compatible surface (Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/
LSTMBias/Load/Mixed, name-pattern dispatch via ``__call__``), but the random
draws come from the framework's JAX PRNG stream (random.py) instead of the
global numpy state, so initialization is reproducible under ``mx.random.seed``
and runs on-device.
"""
from __future__ import annotations

import json
import logging
import re
from math import sqrt
from typing import Dict, Optional

import numpy as np

from .base import string_types

__all__ = ["InitDesc", "Initializer", "Load", "Mixed", "Zero", "One",
           "Constant", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "LSTMBias", "FusedRNN", "register"]

_INIT_REGISTRY: Dict[str, type] = {}


def register(klass):
    """Register an initializer class under its lowercased name."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs describing how a variable asked to be initialized
    (reference initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer: dispatches on parameter name suffix the same way the
    reference does (initializer.py __call__)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, string_types):
            raise TypeError("desc must be an initialization name (str/InitDesc)")
        name = str(desc)
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            _INIT_REGISTRY[klass.lower()](**kwargs)._init_weight(name, arr)
            return
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.startswith("stn_loc") and name.endswith("weight"):
            self._init_zero(name, arr)
        elif name.startswith("stn_loc") and name.endswith("bias"):
            self._init_loc_bias(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("moving_mean") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("moving_inv_var"):
            self._init_one(name, arr)
        else:
            self._init_default(name, arr)

    # -- family defaults ---------------------------------------------------
    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype="float32")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_loc_bias(self, _, arr):
        assert arr.shape[0] == 6
        arr[:] = np.array([1.0, 0, 0, 0, 1.0, 0], dtype="float32")

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("virtual _init_weight")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "covers parameters ending with weight/bias/gamma/beta; name "
            "others explicitly or use Load/Mixed." % name)


@register
class Load:
    """Initialize from an existing dict of arrays, falling back to
    ``default_init`` (reference initializer.Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = dict(param)
        # accept both raw dicts and arg:/aux: prefixed checkpoint dicts
        for key in list(self.param):
            if key.startswith("arg:") or key.startswith("aux:"):
                self.param[key[4:]] = self.param.pop(key)
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            src = self.param[name]
            sshape = tuple(src.shape)
            if sshape != tuple(arr.shape):
                raise ValueError(
                    "Parameter %s cannot be initialized from loading. Shape "
                    "mismatch, target %s vs loaded %s"
                    % (name, arr.shape, sshape))
            arr[:] = src
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise ValueError(
                    "Cannot Initialize parameter %s. Not found in loaded "
                    "param and no default initializer provided." % name)
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)


@register
class Mixed:
    """Dispatch to different initializers by name regex (reference
    initializer.Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            '".*" pattern at the end with default Initializer.' % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0

    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    _init_default = _init_weight


@register
class Uniform(Initializer):
    """U(-scale, scale) weights (reference initializer.Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        from . import random as _random

        arr[:] = _random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    """N(0, sigma) weights (reference initializer.Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        from . import random as _random

        arr[:] = _random.normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    """(Semi-)orthogonal matrix init via QR/SVD (Saxe et al;
    reference initializer.Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        from . import random as _random

        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _random.uniform(-1.0, 1.0, (nout, nin)).asnumpy()
        else:
            tmp = _random.normal(0.0, 1.0, (nout, nin)).asnumpy()
        u, _v, q = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == (nout, nin) else q
        arr[:] = (self.scale * res).reshape(arr.shape)


@register
class Xavier(Initializer):
    """Variance-scaling init (reference initializer.Xavier:344)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        from . import random as _random

        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _random.uniform(-scale, scale, shape)
        elif self.rnd_type == "gaussian":
            arr[:] = _random.normal(0, scale, shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He/MSRA init for PReLU nets (reference initializer.MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        Initializer._init_bilinear(self, name, arr)


@register
class LSTMBias(Initializer):
    """Zero bias with forget gate bias set (reference initializer.LSTMBias).
    Gate order i, f, c, o matches rnn_cell.LSTMCell."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize the single fused RNN parameter vector by unpacking it into
    per-gate weights, applying ``init``, and repacking (reference
    initializer.FusedRNN, backed by rnn_cell parameter layout here)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        from .rnn.rnn_cell import FusedRNNCell

        cell = FusedRNNCell(self._num_hidden, self._num_layers,
                            self._mode, self._bidirectional,
                            forget_bias=self._forget_bias)
        args = cell.unpack_weights({str(name): arr.copy()})
        for pname, parr in args.items():
            desc = InitDesc(pname, getattr(name, "attrs", {}))
            if self._init is None:
                getattr(name, "global_init", None)(desc, parr)
            else:
                self._init(desc, parr)
        packed = cell.pack_weights(args)
        arr[:] = packed[str(name)]

"""Data iterators (parity: /root/reference/python/mxnet/io.py + src/io/).

The reference's C++ iterator pipeline (parser → augmenter → normalize →
batch → prefetch, src/io/io.cc registry) is host-side work; TPU-native
equivalents live here in Python with threaded prefetch (PrefetchingIter ≈
dmlc::ThreadedIter double-buffering, iter_prefetcher.h:28-129) feeding
``jax.device_put``.  `ImageRecordIter` lives in image.py (record-backed),
built on recordio.py.
"""
from __future__ import annotations

import collections
import gzip
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError, string_types
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape (+dtype/layout) of one data stream (reference io.py
    DataDesc).  Unpacks like the legacy (name, shape) tuple."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One batch: data/label lists of NDArray + pad/index bookkeeping
    (reference io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference io.py:126): next/reset/iter protocol with
    getdata/getlabel/getpad/getindex hooks."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError

    # -- mid-epoch resume state (guardian rollback / deterministic replay) --
    def state_dict(self) -> dict:
        """Position snapshot (epoch cursor, shuffle order) as plain host
        data.  Restoring it with :meth:`set_state` on an iterator built
        from the same inputs replays the exact remaining batch sequence —
        the contract guardian rollback and mid-epoch resume depend on."""
        raise NotImplementedError(
            "%s does not support state capture" % type(self).__name__)

    def set_state(self, state: dict) -> None:
        """Restore a :meth:`state_dict` snapshot."""
        raise NotImplementedError(
            "%s does not support state capture" % type(self).__name__)


def _init_data(data, allow_empty, default_name):
    """Normalize input data to a list of (name, numpy) pairs (reference
    io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError(
            "Input must be NDArray, numpy.ndarray, a list of them or dict "
            "with them as values")
    out = collections.OrderedDict()
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle + last-batch handling
    (reference io.py:453)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]

        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
            self._shuffle_perm = idx
        else:
            self._shuffle_perm = None
        self.idx = np.arange(self.num_data)

        # discard: drop the tail so every batch is full (static shapes — the
        # jit-friendly default for TPU); pad/roll_over keep reference behavior
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.num_data = new_n
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def state_dict(self):
        perm = self._shuffle_perm
        return {"cursor": int(self.cursor),
                "shuffle_perm": None if perm is None else perm.copy()}

    def set_state(self, state):
        perm = state.get("shuffle_perm")
        if perm is not None:
            perm = np.asarray(perm)
            cur = self._shuffle_perm if self._shuffle_perm is not None \
                else np.arange(len(perm))
            if not np.array_equal(perm, cur):
                # re-order through the original layout: undo this
                # instance's own shuffle, then apply the saved one
                inv = np.argsort(cur)
                self.data = [(k, v[inv][perm]) for k, v in self.data]
                self.label = [(k, v[inv][perm]) for k, v in self.label]
                self._shuffle_perm = perm
        self.cursor = int(state["cursor"])


class ResizeIter(DataIter):
    """Resize another iterator to ``size`` batches per epoch (reference
    io.py:216)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def state_dict(self):
        return {"cur": int(self.cur), "inner": self.data_iter.state_dict()}

    def set_state(self, state):
        self.data_iter.set_state(state["inner"])
        self.cur = int(state["cur"])


#: queue sentinel marking a source iterator's end of epoch
_END_OF_EPOCH = object()

#: telemetry instruments for the prefetch pipeline (created on first
#: enabled use — see PrefetchingIter._prefetch_metrics)
_PREFETCH_TELEM = None


class PrefetchingIter(DataIter):
    """Producer/consumer prefetch over one or more source iterators, so host
    batch preparation overlaps device compute (the capability of the
    reference's dmlc::ThreadedIter-backed PrefetchingIter, python/mxnet/
    io.py:281 — rebuilt here on a bounded ``queue.Queue`` pipeline with
    sentinel shutdown instead of event-pair handshakes).

    Each source gets one worker thread pushing batches into a depth-bounded
    queue; ``next()`` pops one batch per source and concatenates the
    data/label lists.  ``prefetch_depth`` > 1 smooths bursty sources (the
    event-pair scheme caps at double buffering).  ``reset`` tears the
    pipeline down (poison via a stop flag + queue drain), resets the
    sources, and restarts — epoch boundaries are rare so worker restart
    costs nothing measurable.
    """

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        super().__init__()
        self.iters = list(iters) if isinstance(iters, (list, tuple)) \
            else [iters]
        if not self.iters:
            raise ValueError("PrefetchingIter needs at least one source")
        self.n_iter = len(self.iters)
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._depth = max(1, int(prefetch_depth))
        self.batch_size = self.provide_data[0][1][0]
        self.current_batch = None
        self._queues = []
        self._threads = []
        self._stop = None
        self._exhausted = False
        self._consumed = 0  # batches the CONSUMER has popped this epoch
        self._spin_up()

    # -- pipeline lifecycle -------------------------------------------------
    def _spin_up(self):
        import queue as _queue

        self._stop = threading.Event()
        self._queues = [_queue.Queue(maxsize=self._depth)
                        for _ in range(self.n_iter)]
        self._threads = []
        for src, q in zip(self.iters, self._queues):
            t = threading.Thread(target=self._produce,
                                 args=(src, q, self._stop), daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _produce(src, q, stop):
        while not stop.is_set():
            try:
                item = src.next()
            except StopIteration:
                item = _END_OF_EPOCH
            except Exception as exc:  # surface source errors to the consumer
                item = exc
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    break
                except Exception:  # queue.Full — re-check stop
                    continue
            if item is _END_OF_EPOCH or isinstance(item, Exception):
                return

    def _tear_down(self, wait=True):
        if self._stop is None:
            return
        self._stop.set()
        for q in self._queues:  # unblock producers stuck on a full queue
            try:
                while True:
                    q.get_nowait()
            except Exception:  # queue.Empty
                pass
        for t in self._threads:
            # wait for workers to leave src.next() before the caller touches
            # the (non-thread-safe) sources again; __del__ uses a bounded
            # join since nothing observes the sources afterwards
            t.join() if wait else t.join(timeout=1.0)
        self._threads = []
        self._queues = []

    def close(self):
        """Tear the worker threads down NOW — for abandoning an epoch
        mid-iteration (early stop, exception unwind), where waiting for
        ``reset()`` or garbage collection would leave producers parked on
        live queues holding their sources.  Idempotent; a later
        ``reset()`` restarts the pipeline."""
        self._tear_down(wait=True)
        self._exhausted = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self._tear_down(wait=False)
        except Exception:  # interpreter teardown: globals may be gone
            pass

    # -- DataIter surface ---------------------------------------------------
    def _renamed(self, descs_per_iter, rename):
        if rename is None:
            return [d for descs in descs_per_iter for d in descs]
        out = []
        for mapping, descs in zip(rename, descs_per_iter):
            for d in descs:
                d = d if isinstance(d, DataDesc) else DataDesc(d[0], d[1])
                out.append(DataDesc(mapping[d.name], d.shape, d.dtype))
        return out

    @property
    def provide_data(self):
        return self._renamed([i.provide_data for i in self.iters],
                             self.rename_data)

    @property
    def provide_label(self):
        return self._renamed([i.provide_label for i in self.iters],
                             self.rename_label)

    def reset(self):
        self._tear_down()
        for src in self.iters:
            src.reset()
        self._exhausted = False
        self._consumed = 0
        self._spin_up()

    def state_dict(self):
        """Forward to the wrapped iters, fixed up for prefetch depth: the
        workers have already pulled ahead of the consumer, so the
        captured position is the **consumed-batch** cursor, not the
        source's read-ahead cursor.  Sources must expose a top-level
        ``cursor`` (NDArrayIter/MNISTIter/CSVIter do); shuffle order
        passes through untouched."""
        states = []
        for src in self.iters:
            s = dict(src.state_dict())
            if "cursor" not in s:
                raise ValueError(
                    "PrefetchingIter state capture needs cursor-based "
                    "sources; %s has none" % type(src).__name__)
            s["cursor"] = (self._consumed - 1) * src.batch_size
            states.append(s)
        return {"consumed": int(self._consumed), "sources": states}

    def set_state(self, state):
        self._tear_down()
        for src, s in zip(self.iters, state["sources"]):
            src.set_state(s)
        self._consumed = int(state["consumed"])
        self._exhausted = False
        self._spin_up()

    @staticmethod
    def _prefetch_metrics():
        """Lazy global-registry instruments shared by all prefetchers."""
        global _PREFETCH_TELEM
        if _PREFETCH_TELEM is None:
            from . import telemetry as _tm

            reg = _tm.registry()
            _PREFETCH_TELEM = {
                "starved_ms": reg.counter(
                    "mxtpu_prefetch_starvation_ms_total",
                    "Time the consumer blocked on empty prefetch queues."),
                "occupancy": reg.histogram(
                    "mxtpu_prefetch_queue_occupancy",
                    "Prefetch queue fill observed at each batch pop.",
                    start=1.0, factor=2.0, count=8),
                "batches": reg.counter("mxtpu_prefetch_batches_total",
                                       "Batches popped from the pipeline."),
            }
        return _PREFETCH_TELEM

    def iter_next(self):
        if self._exhausted:  # workers are gone; don't block on dead queues
            return False
        from . import telemetry as _tm

        if _tm.enabled():
            m = self._prefetch_metrics()
            m["occupancy"].observe(sum(q.qsize() for q in self._queues))
            t0 = time.monotonic()
            parts = [q.get() for q in self._queues]
            m["starved_ms"].inc((time.monotonic() - t0) * 1e3)
        else:
            m = None
            parts = [q.get() for q in self._queues]
        for p in parts:
            if isinstance(p, Exception):
                raise p
        ended = [p is _END_OF_EPOCH for p in parts]
        if any(ended):
            if not all(ended):
                raise RuntimeError(
                    "prefetch sources ended at different batch counts")
            self._exhausted = True
            return False
        if m is not None:  # the end-of-epoch pop is not a batch
            m["batches"].inc()
        self._consumed += 1
        first = parts[0]
        if any(p.pad != first.pad for p in parts):
            raise RuntimeError("prefetch sources disagree on batch padding")
        self.current_batch = DataBatch(
            [a for p in parts for a in p.data],
            [a for p in parts for a in (p.label or [])],
            first.pad, first.index)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("bad MNIST image file magic %d in %s" % (magic, path))
        data = np.frombuffer(f.read(num * rows * cols), dtype=np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("bad MNIST label file magic %d in %s" % (magic, path))
        return np.frombuffer(f.read(num), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """MNIST idx-ubyte reader (reference src/io/iter_mnist.cc:61,241), with
    the same flat/shuffle/partition options, built on NDArrayIter batching."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=0, part_index=0, num_parts=1,
                 **kwargs):
        images = _read_idx_images(image).astype(np.float32) / 255.0
        labels = _read_idx_labels(label).astype(np.float32)
        if flat:
            images = images.reshape(len(images), -1)
        else:
            images = images.reshape(len(images), 1, images.shape[1], images.shape[2])
        if num_parts > 1:  # rank sharding (dist-training InputSplit semantics)
            part = len(images) // num_parts
            images = images[part_index * part:(part_index + 1) * part]
            labels = labels[part_index * part:(part_index + 1) * part]
        if shuffle:
            rng = np.random.RandomState(seed)
            idx = rng.permutation(len(images))
            images, labels = images[idx], labels[idx]
        super().__init__(images, labels, batch_size, shuffle=False,
                         last_batch_handle="discard")


class CSVIter(DataIter):
    """CSV reader (reference src/io/iter_csv.cc:41,132): streams
    ``data_csv`` (+ optional ``label_csv``) in ``batch_size`` rows with
    ``data_shape`` reshaping; tail batches are zero-padded like the
    reference's batch loader."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        self._data = data.reshape((-1,) + self.data_shape)
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
            self._label = label.reshape((-1,) + self.label_shape)
        else:
            self._label = np.zeros((self._data.shape[0],) + self.label_shape,
                                   dtype=np.float32)
        self.num_data = self._data.shape[0]
        self.cursor = -batch_size
        self.round_batch = round_batch

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size,) + self.label_shape)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _slice(self, src):
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            return [array(src[self.cursor:end])]
        out = np.zeros((self.batch_size,) + src.shape[1:], dtype=src.dtype)
        tail = src[self.cursor:]
        out[:len(tail)] = tail
        if self.round_batch:
            out[len(tail):] = src[:self.batch_size - len(tail)]
        return [array(out)]

    def getdata(self):
        return self._slice(self._data)

    def getlabel(self):
        return self._slice(self._label)

    def getpad(self):
        end = self.cursor + self.batch_size
        return max(0, end - self.num_data)

    def state_dict(self):
        return {"cursor": int(self.cursor)}

    def set_state(self, state):
        self.cursor = int(state["cursor"])


def ImageRecordIter(**kwargs):
    """RecordIO image iterator (reference: C++ ImageRecordIter registered in
    src/io/io.cc:9-23, exposed as mx.io.ImageRecordIter). Delegates to the
    Python pipeline in mxnet_tpu.image."""
    from . import image

    return image.ImageRecordIter(**kwargs)

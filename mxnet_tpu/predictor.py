"""Predictor — the serving/inference path.

TPU-native redesign of the reference C predict API
(/root/reference/src/c_api/c_predict_api.cc:41-280: load symbol JSON +
param blob -> filter arg/aux dicts -> InferShape -> static bind -> SetInput/
Forward/GetOutput) plus the amalgamation deployment story
(/root/reference/amalgamation/README.md:1-14).  Two artifacts:

  * ``Predictor`` — loads a checkpoint (symbol JSON + ``.params``), binds a
    static inference executor (no grads), and serves ``forward()``.
  * ``Predictor.export(path)`` / ``load_exported(path)`` — ahead-of-time
    compilation via ``jax.export``: the whole jitted forward (params baked
    in) serialized as a portable StableHLO artifact, reloadable without the
    model-building Python code — the amalgamation equivalent.
"""
from __future__ import annotations

import io
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from .context import Context, cpu

__all__ = ["Predictor", "load_exported"]

_EXPORT_MAGIC = b"MXTPUEXP1"


class Predictor:
    """Static bound forward over a trained (symbol, params) checkpoint.

    Parameters
    ----------
    symbol : Symbol | str
        A Symbol, a path to ``prefix-symbol.json``, or a JSON string.
    params : dict | str
        ``{name: NDArray}`` (``arg:``/``aux:`` prefixes allowed, as stored
        by ``save_checkpoint``) or a path to a ``.params`` file.
    input_shapes : dict
        ``{input_name: shape}`` — static shapes, like MXPredCreate's
        input_keys/shape arrays.
    """

    def __init__(self, symbol, params, input_shapes: Dict[str, Sequence[int]],
                 ctx: Optional[Context] = None, dtype=np.float32):
        from . import ndarray as nd
        from . import symbol as sym

        if isinstance(symbol, str):
            if os.path.exists(symbol):
                symbol = sym.load(symbol)
            else:
                symbol = sym.load_json(symbol)
        if isinstance(params, str):
            params = nd.load(params)
        arg_params, aux_params = {}, {}
        for k, v in params.items():
            tp, _, name = k.partition(":")
            if tp == "arg":
                arg_params[name] = v
            elif tp == "aux":
                aux_params[name] = v
            else:
                arg_params[k] = v

        self._ctx = ctx or cpu()
        self._symbol = symbol
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self._dtype = np.dtype(dtype)

        arg_shapes, _, aux_shapes = symbol.infer_shape(**self._input_shapes)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes from the given inputs")
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        args = {}
        self._synthesized = set()
        for name, shape in zip(arg_names, arg_shapes):
            if name in self._input_shapes:
                args[name] = nd.zeros(shape, self._ctx, dtype=self._dtype)
            elif name in arg_params:
                if tuple(arg_params[name].shape) != tuple(shape):
                    raise MXNetError(
                        "param %s shape %s does not match inferred %s"
                        % (name, arg_params[name].shape, shape))
                p = arg_params[name]
                # reshape() passes live device NDArrays: share, don't copy
                args[name] = p if isinstance(p, nd.NDArray) else \
                    nd.array(p, self._ctx)
            else:
                # reference MXPredCreate allocates missing args without
                # initializing them (c_predict_api.cc:190-195); we
                # zero-fill for determinism — loss labels in a saved
                # training symbol bind as zeros at inference
                args[name] = nd.zeros(shape, self._ctx, dtype=self._dtype)
                self._synthesized.add(name)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            if name not in aux_params:
                raise MXNetError("missing auxiliary state %r" % name)
            a = aux_params[name]
            aux[name] = a if isinstance(a, nd.NDArray) else \
                nd.array(a, self._ctx)

        self._exec = symbol.bind(self._ctx, args, args_grad=None,
                                 grad_req="null", aux_states=aux)
        self._input_names = list(self._input_shapes)

    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, ctx=None,
                        dtype=np.float32):
        """Build a predictor straight from ``save_checkpoint`` files
        (``prefix-symbol.json`` + ``prefix-%04d.params`` — the file pair
        MXPredCreate consumes in the reference)."""
        return cls("%s-symbol.json" % prefix,
                   "%s-%04d.params" % (prefix, epoch),
                   input_shapes, ctx=ctx, dtype=dtype)

    # -- MXPredSetInput / MXPredForward / MXPredGetOutput parity ----------
    def set_input(self, name, value):
        if name not in self._input_shapes:
            raise MXNetError("unknown input %r" % name)
        self._exec.arg_dict[name][:] = value

    def forward(self, **inputs):
        for name, value in inputs.items():
            self.set_input(name, value)
        self._exec.forward(is_train=False)
        return self.get_outputs()

    def get_output(self, index):
        return self._exec.outputs[index]

    def get_outputs(self):
        return list(self._exec.outputs)

    def reshape(self, input_shapes):
        """Re-bind for new static input shapes (MXPredReshape,
        c_predict_api.cc:150-210).  Inputs not named keep their current
        shapes, matching the reference."""
        # synthesized (zero-filled) args are per-shape scratch, not model
        # params: drop them so the new bind re-synthesizes at its shapes
        params = {("arg:%s" % k): v for k, v in self._exec.arg_dict.items()
                  if k not in self._input_shapes
                  and k not in self._synthesized}
        params.update({("aux:%s" % k): v
                       for k, v in self._exec.aux_dict.items()})
        merged = dict(self._input_shapes)
        merged.update({k: tuple(v) for k, v in input_shapes.items()})
        return Predictor(self._symbol, params, merged, self._ctx,
                         self._dtype)

    # -- AOT export (amalgamation equivalent) -----------------------------
    def export(self, path):
        """Serialize the jitted forward (params baked in) as a portable
        ``jax.export`` StableHLO artifact + output metadata."""
        import jax
        from jax import export as jexport

        plan = self._exec._plan
        # same stages as the live Executor forward (_get_fwd): mixed-
        # precision cast + ctx-group placement, so the exported program is
        # the program the Predictor serves
        cast = self._exec._cast_fn()
        placement = self._exec._placement
        params = {k: v._data for k, v in self._exec.arg_dict.items()
                  if k not in self._input_shapes}
        aux = {k: v._data for k, v in self._exec.aux_dict.items()}
        input_names = self._input_names

        def serve(*inputs):
            args = dict(params)
            args.update(dict(zip(input_names, inputs)))
            outs, _ = plan.run(cast(args), aux, None, False,
                               placement=placement)
            return tuple(outs)

        abstract = [jax.ShapeDtypeStruct(self._input_shapes[n], self._dtype)
                    for n in input_names]
        exported = jexport.export(jax.jit(serve))(*abstract)
        blob = exported.serialize()
        meta = json.dumps({
            "inputs": [[n, list(self._input_shapes[n]), str(self._dtype)]
                       for n in input_names],
            "outputs": self._symbol.list_outputs()}).encode()
        with open(path, "wb") as f:
            f.write(_EXPORT_MAGIC)
            f.write(len(meta).to_bytes(8, "little"))
            f.write(meta)
            f.write(blob)
        return path


class _ExportedPredictor:
    """Reloaded AOT artifact: callable without the original model code."""

    def __init__(self, exported, meta):
        self._exported = exported
        self._meta = meta
        self.input_names = [m[0] for m in meta["inputs"]]
        self.output_names = meta["outputs"]

    def forward(self, **inputs):
        import jax.numpy as jnp

        vals = []
        for name, shape, dtype in self._meta["inputs"]:
            if name not in inputs:
                raise MXNetError("missing input %r" % name)
            vals.append(jnp.asarray(np.asarray(inputs[name], dtype=dtype)))
        return list(self._exported.call(*vals))


def load_exported(path):
    """Reload an artifact written by ``Predictor.export`` (the other half of
    the amalgamation story: deploy-time needs only this loader)."""
    from jax import export as jexport

    with open(path, "rb") as f:
        magic = f.read(len(_EXPORT_MAGIC))
        if magic != _EXPORT_MAGIC:
            raise MXNetError("%s is not an exported predictor artifact" % path)
        mlen = int.from_bytes(f.read(8), "little")
        meta = json.loads(f.read(mlen).decode())
        blob = f.read()
    exported = jexport.deserialize(blob)
    return _ExportedPredictor(exported, meta)

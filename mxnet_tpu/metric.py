"""Evaluation metrics (parity: /root/reference/python/mxnet/metric.py).

EvalMetric.update takes lists of label/pred NDArrays.  ``asnumpy()`` here is
THE hard sync point of the training loop (reference base_module.py:480 —
metric update forces WaitToRead), so metrics are computed on host numpy.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Union

import numpy
import numpy as np  # shadowed below by the np() factory; use `numpy` internally

from .base import string_types
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "Torch", "CustomMetric", "np", "create", "check_label_shapes"]


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels %s does not match shape of predictions %s"
            % (label_shape, pred_shape))


class EvalMetric:
    """Base metric accumulating (sum_metric, num_inst) (reference
    metric.py:10-84)."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError("virtual EvalMetric.update")

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
        self._device_sum = None  # lazily-synced on-device accumulator

    def _accumulate_device(self, value, count):
        """Accumulate a device scalar without a host round-trip.  The sync
        moves from every batch to every get() call (Speedometer cadence), so
        the dispatch queue stays ahead of the host — the TPU analogue of the
        reference's async-engine metric design where asnumpy was the only
        sync point."""
        if self._device_sum is None:
            self._device_sum = value
        else:
            self._device_sum = self._device_sum + value
        self.num_inst += count

    def _materialize(self):
        if self._device_sum is not None:
            self.sum_metric += float(self._device_sum)
            self._device_sum = None

    def get(self):
        self._materialize()
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [x / y if y != 0 else float("nan")
                  for x, y in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference metric.py:86)."""

    def __init__(self, **kwargs):
        super().__init__("composite")
        try:
            self.metrics = kwargs["metrics"]
        except KeyError:
            self.metrics = []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}".format(
                index, len(self.metrics)))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        results = []
        for metric in self.metrics:
            result = metric.get()
            names.append(result[0])
            results.append(result[1])
        return (names, results)


class Accuracy(EvalMetric):
    """Classification accuracy (reference metric.py:132)."""

    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            if isinstance(pred_label, NDArray) and isinstance(label, NDArray):
                # on-device compare + lazy sync (see _accumulate_device)
                import jax
                import jax.numpy as jnp

                pred = pred_label._data
                lab = label._data
                if pred.ndim > 1 and pred.shape != lab.shape:
                    pred = jnp.argmax(pred, axis=1)
                pred = pred.astype(jnp.int32).ravel()
                # labels usually live on one device while preds may be
                # mesh-sharded: colocate before the eager compare
                if getattr(lab, "sharding", None) != getattr(
                        pred, "sharding", None):
                    lab = jax.device_put(lab, pred.sharding)
                correct = jnp.sum(pred == lab.astype(jnp.int32).ravel())
                self._accumulate_device(correct, int(lab.size))
                continue
            pred = pred_label.asnumpy() if isinstance(pred_label, NDArray) \
                else numpy.asarray(pred_label)
            if pred.ndim > 1 and pred.shape != label.shape:
                pred = numpy.argmax(pred, axis=1)
            pred = pred.astype("int32")
            label_np = label.asnumpy().astype("int32") \
                if isinstance(label, NDArray) \
                else numpy.asarray(label).astype("int32")
            check_label_shapes(label_np, pred)
            self.sum_metric += (pred.flat == label_np.flat).sum()
            self.num_inst += len(pred.flat)


class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference metric.py:152)."""

    def __init__(self, **kwargs):
        super().__init__("top_k_accuracy")
        try:
            self.top_k = kwargs["top_k"]
        except KeyError:
            self.top_k = 1
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, "Predictions should be no more than 2 dims"
            pred = numpy.argsort(pred_label.asnumpy().astype("float32"), axis=1)
            label_np = label.asnumpy().astype("int32")
            check_label_shapes(label_np, pred)
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self.sum_metric += (pred.flat == label_np.flat).sum()
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred[:, num_classes - 1 - j].flat == label_np.flat).sum()
            self.num_inst += num_samples


class F1(EvalMetric):
    """Binary F1 score (reference metric.py:183)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype("int32")
            pred_label = numpy.argmax(pred_np, axis=1)
            check_label_shapes(label_np, pred_label)
            if len(numpy.unique(label_np)) > 2:
                raise ValueError("F1 currently only supports binary classification.")
            true_positives, false_positives, false_negatives = 0.0, 0.0, 0.0
            for y_pred, y_true in zip(pred_label, label_np):
                if y_pred == 1 and y_true == 1:
                    true_positives += 1.0
                elif y_pred == 1 and y_true == 0:
                    false_positives += 1.0
                elif y_pred == 0 and y_true == 1:
                    false_negatives += 1.0
            if true_positives + false_positives > 0:
                precision = true_positives / (true_positives + false_positives)
            else:
                precision = 0.0
            if true_positives + false_negatives > 0:
                recall = true_positives / (true_positives + false_negatives)
            else:
                recall = 0.0
            if precision + recall > 0:
                f1_score = 2 * precision * recall / (precision + recall)
            else:
                f1_score = 0.0
            self.sum_metric += f1_score
            self.num_inst += 1


class Perplexity(EvalMetric):
    """Perplexity = exp(mean NLL) (reference metric.py:230)."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy().astype("int32").reshape(-1)
            pred_np = pred.asnumpy()
            pred_np = pred_np.reshape(-1, pred_np.shape[-1] if self.axis in (-1, pred_np.ndim - 1)
                                      else pred_np.shape[self.axis])
            assert label_np.shape[0] == pred_np.shape[0], \
                "shape mismatch: %s vs %s" % (label.shape, pred.shape)
            probs = pred_np[numpy.arange(label_np.shape[0]), label_np]
            if self.ignore_label is not None:
                ignore = (label_np == self.ignore_label).astype(probs.dtype)
                probs = probs * (1 - ignore) + ignore
                num -= int(ignore.sum())
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label_np.shape[0]
        self.sum_metric += math.exp(loss / max(1, num)) * num
        self.num_inst += num


class MAE(EvalMetric):
    """Mean absolute error (reference metric.py:280)."""

    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy()
            pred_np = pred.asnumpy()
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += numpy.abs(label_np - pred_np).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    """Mean squared error (reference metric.py:294)."""

    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy()
            pred_np = pred.asnumpy()
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += ((label_np - pred_np) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    """Root mean squared error (reference metric.py:308)."""

    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy()
            pred_np = pred.asnumpy()
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label_np - pred_np) ** 2.0).mean())
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    """Cross-entropy of softmax outputs vs integer labels (reference
    metric.py:335)."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy()
            pred_np = pred.asnumpy()
            label_np = label_np.ravel()
            assert label_np.shape[0] == pred_np.shape[0]
            prob = pred_np[numpy.arange(label_np.shape[0]), numpy.int64(label_np)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label_np.shape[0]


class Loss(EvalMetric):
    """Mean of the raw outputs — for MakeLoss-style networks whose output IS
    the loss."""

    def __init__(self, name="loss"):
        super().__init__(name)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += float(pred.asnumpy().sum())
            self.num_inst += pred.size


class Torch(Loss):
    """Parity alias for reference metric.Torch (mean of outputs)."""

    def __init__(self, name="torch"):
        super().__init__(name)


class CustomMetric(EvalMetric):
    """Wrap a feval(label, pred) -> float function (reference metric.py:370)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label_np = label.asnumpy()
            pred_np = pred.asnumpy()
            reval = self._feval(label_np, pred_np)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy feval (reference metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create a metric by name / callable / list (reference metric.create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(child)
        return composite
    metrics = {
        "acc": Accuracy, "accuracy": Accuracy, "ce": CrossEntropy,
        "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
        "top_k_accuracy": TopKAccuracy, "topkaccuracy": TopKAccuracy,
        "perplexity": Perplexity, "cross-entropy": CrossEntropy,
        "torch": Torch, "loss": Loss, "composite": CompositeEvalMetric,
    }
    try:
        return metrics[metric.lower()](**kwargs)
    except Exception:
        raise ValueError("Metric must be either callable or in {}".format(
            sorted(metrics)))

"""mxnet_tpu.guardian — numeric-anomaly detection and self-healing training.

Every *crash* mode in this stack is survivable (kvstore kill -9, elastic
churn, serving failover), but a silently-wrong step — a NaN/Inf
gradient, a loss/grad-norm spike, a bit-flipped tensor from flaky
hardware — poisons the parameters and every replica that pulls them.
This module is the training-side half of the answer (the fleet-side half
is the kvstore server's non-finite push NACK):

* **Detection.**  The fused train step folds one ``isfinite``
  all-reduce over every gradient and output plus a global grad-norm into
  the compiled program (``Executor._get_fused_step(guard=True)``) — the
  check itself costs no host round-trip; the verdict is read where the
  step already syncs.  The unfused path checks host-visible gradients
  directly.  A rolling-median spike detector
  (``MXNET_GUARDIAN_SPIKE_MULT`` × the median of the last
  ``MXNET_GUARDIAN_SPIKE_WINDOW`` observations) catches
  huge-but-finite corruption (the classic exponent bit-flip).

* **Graded response.**  ``Guardian.observe`` walks a ladder:
  *skip-batch* (the fused guard already skipped non-finite updates on
  device), then *LR re-warm* (ramp from ``MXNET_GUARDIAN_REWARM_FACTOR``
  back to 1.0 over ``MXNET_GUARDIAN_REWARM_STEPS`` applied steps), then
  *rollback* to the last-good snapshot.  More than
  ``MXNET_GUARDIAN_ROLLBACK_MAX`` rollbacks raises
  :class:`GuardianAbort` — at that point the corruption is not
  transient and a human should look.

* **Last-good ring.**  ``Module.fit`` offers a snapshot every
  ``MXNET_GUARDIAN_SNAPSHOT_EVERY`` batches; the guardian keeps the
  newest ``MXNET_GUARDIAN_RING`` of them.  A snapshot captures params,
  optimizer/updater state, the framework PRNG stream
  (``mx.random.get_state``) and the data-iterator position
  (``DataIter.state_dict``), so rollback-and-replay is bit-deterministic:
  the replayed steps see the same batches, the same stochastic schedule,
  and (the injected fault having already fired) clean gradients.

Cost model: mirrors ``faults``/``telemetry`` — disabled (the default),
every hook is one module-global read.  Activate with
``MXNET_GUARDIAN=1`` or :func:`enable`.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional, Tuple

from .base import MXNetError, env, register_env

__all__ = ["Guardian", "GuardianAbort", "enable", "disable", "enabled",
           "current_lr_mult", "stats", "reset_stats"]

register_env("MXNET_GUARDIAN", 0, int,
             "Master switch for the training guardian (fused-step "
             "numeric guard, spike detector, graded skip/re-warm/"
             "rollback response). Off: every hook is one global read.")
register_env("MXNET_GUARDIAN_SPIKE_MULT", 10.0, float,
             "A monitored scalar (grad-norm, loss) above this multiple "
             "of its rolling median counts as an anomaly.")
register_env("MXNET_GUARDIAN_SPIKE_WINDOW", 32, int,
             "Rolling-median window (in applied steps) for the spike "
             "detector.")
register_env("MXNET_GUARDIAN_WARMUP", 8, int,
             "Applied steps of history before the spike detector arms "
             "(non-finite detection is armed from step one).")
register_env("MXNET_GUARDIAN_SKIP_MAX", 2, int,
             "Consecutive anomalous steps answered by skip-batch before "
             "the ladder escalates.")
register_env("MXNET_GUARDIAN_REWARM_STEPS", 50, int,
             "LR re-warm ramp length in applied steps; 0 removes the "
             "re-warm rung (skip escalates straight to rollback).")
register_env("MXNET_GUARDIAN_REWARM_FACTOR", 0.1, float,
             "LR multiplier at the start of a re-warm ramp.")
register_env("MXNET_GUARDIAN_ROLLBACK_MAX", 2, int,
             "Rollbacks per fit before the guardian gives up and raises "
             "GuardianAbort.")
register_env("MXNET_GUARDIAN_RING", 2, int,
             "Last-good snapshots kept in the in-memory retention ring.")
register_env("MXNET_GUARDIAN_SNAPSHOT_EVERY", 50, int,
             "Batches between last-good ring snapshots in Module.fit.")

# the single hot-path gate (faults' plan-is-None idiom)
_ACTIVE = bool(env("MXNET_GUARDIAN", 0, int))

#: the Guardian currently steering the learning rate (re-warm ramp);
#: optimizer._get_lr and Executor.fused_step consult this — one global
#: read when no ramp is live
_governor: Optional["Guardian"] = None

_stats_lock = threading.Lock()
_STATS = {"anomalies": 0, "skips": 0, "rewarms": 0, "rollbacks": 0,
          "snapshots": 0}


class GuardianAbort(MXNetError):
    """Raised when the rollback budget is exhausted: the anomaly is not
    transient (bad data shard, diverged hypers, sick chip) and another
    automatic replay would loop forever."""


def enable() -> None:
    global _ACTIVE
    _ACTIVE = True


def disable() -> None:
    global _ACTIVE, _governor
    _ACTIVE = False
    _governor = None


def enabled() -> bool:
    return _ACTIVE


def current_lr_mult() -> float:
    """The live re-warm LR multiplier (1.0 when no ramp is active)."""
    g = _governor
    return 1.0 if g is None else g.lr_mult()


def stats() -> dict:
    """Process-wide guardian counters (bench embeds these in BENCH
    records; chaos scenarios assert on them)."""
    with _stats_lock:
        return dict(_STATS)


def reset_stats() -> None:
    with _stats_lock:
        for k in _STATS:
            _STATS[k] = 0


def _bump(key, n=1):
    with _stats_lock:
        _STATS[key] += n


def _telemetry_anomaly(kind, step, value):
    from . import telemetry as _tm

    if not _tm.enabled():
        return
    _tm.labeled_counter("mxtpu_guardian_anomalies_total", "kind",
                        "Numeric anomalies the guardian detected.").inc(kind)
    _tm.log_event("guardian_anomaly", kind=kind, step=step, value=value)


def _telemetry_action(action, step):
    from . import telemetry as _tm

    if not _tm.enabled():
        return
    _tm.counter("mxtpu_guardian_%ss_total" % action,
                "Guardian %s responses." % action).inc()
    _tm.log_event("guardian_action", action=action, step=step)


class Guardian:
    """One training run's anomaly detector + response policy.

    ``observe(finite, gnorm, loss)`` is called once per step and returns
    the action the caller must take: ``"ok"`` (apply/continue),
    ``"skip"`` (do not apply this batch), ``"rewarm"`` (skip AND a fresh
    LR ramp just started), or ``"rollback"`` (restore
    :meth:`rollback_target` and replay).  The ladder escalates with
    *consecutive* anomalies and resets on any clean step.

    ``clock`` is injectable for tests (fake-clock unit tests drive the
    ladder without sleeping); it only feeds timestamps in events/stats,
    never decisions — determinism of the response sequence is part of
    the replay contract.
    """

    def __init__(self, clock: Callable[[], float] = None,
                 spike_mult: Optional[float] = None,
                 spike_window: Optional[int] = None,
                 warmup: Optional[int] = None,
                 skip_max: Optional[int] = None,
                 rewarm_steps: Optional[int] = None,
                 rewarm_factor: Optional[float] = None,
                 rollback_max: Optional[int] = None,
                 ring: Optional[int] = None,
                 snapshot_every: Optional[int] = None):
        import time

        def _knob(val, name, typ):
            return typ(env(name)) if val is None else typ(val)

        self.clock = clock or time.monotonic
        self.spike_mult = _knob(spike_mult, "MXNET_GUARDIAN_SPIKE_MULT",
                                float)
        self.spike_window = _knob(spike_window,
                                  "MXNET_GUARDIAN_SPIKE_WINDOW", int)
        self.warmup = _knob(warmup, "MXNET_GUARDIAN_WARMUP", int)
        self.skip_max = _knob(skip_max, "MXNET_GUARDIAN_SKIP_MAX", int)
        self.rewarm_steps = _knob(rewarm_steps,
                                  "MXNET_GUARDIAN_REWARM_STEPS", int)
        self.rewarm_factor = _knob(rewarm_factor,
                                   "MXNET_GUARDIAN_REWARM_FACTOR", float)
        self.rollback_max = _knob(rollback_max,
                                  "MXNET_GUARDIAN_ROLLBACK_MAX", int)
        self.ring_size = max(1, _knob(ring, "MXNET_GUARDIAN_RING", int))
        self.snapshot_every = _knob(snapshot_every,
                                    "MXNET_GUARDIAN_SNAPSHOT_EVERY", int)

        self._gnorms: deque = deque(maxlen=max(1, self.spike_window))
        self._losses: deque = deque(maxlen=max(1, self.spike_window))
        self._consec = 0
        self._step = 0
        self._rewarm_left = 0
        self._rollbacks = 0
        self._last_snap_step: Optional[int] = None
        self._ring: List[Tuple[int, dict]] = []  # (step, snapshot)
        self.history: List[Tuple[str, int, float]] = []  # (action, step, ts)

    # -- spike machinery ---------------------------------------------------
    @staticmethod
    def _median(window) -> Optional[float]:
        if not window:
            return None
        vals = sorted(window)
        mid = len(vals) // 2
        return vals[mid] if len(vals) % 2 else \
            0.5 * (vals[mid - 1] + vals[mid])

    def _spiked(self, window, value) -> bool:
        if value is None or len(window) < max(1, self.warmup):
            return False
        med = self._median(window)
        # only a positive median gives the multiplicative test meaning
        # (losses can legitimately be <= 0 — e.g. log-likelihoods)
        return med is not None and med > 0 and value > self.spike_mult * med

    # -- the ladder --------------------------------------------------------
    def observe(self, finite: bool = True, gnorm: Optional[float] = None,
                loss: Optional[float] = None) -> str:
        """Feed one step's verdicts; -> "ok" | "skip" | "rewarm" |
        "rollback" (the caller acts on it — see class docstring)."""
        import math

        self._step += 1
        kind = None
        if not finite or \
                (gnorm is not None and not math.isfinite(gnorm)) or \
                (loss is not None and not math.isfinite(loss)):
            kind = "nonfinite"
        elif self._spiked(self._gnorms, gnorm):
            kind = "grad_spike"
        elif self._spiked(self._losses, loss):
            kind = "loss_spike"

        if kind is None:
            if gnorm is not None:
                self._gnorms.append(gnorm)
            if loss is not None:
                self._losses.append(loss)
            self._consec = 0
            if self._rewarm_left > 0:
                self._rewarm_left -= 1
                if self._rewarm_left == 0:
                    self._set_governor(False)
            return "ok"

        self._consec += 1
        _bump("anomalies")
        _telemetry_anomaly(kind, self._step,
                           gnorm if kind != "loss_spike" else loss)
        if self._consec <= self.skip_max:
            action = "skip"
        elif self.rewarm_steps > 0 and \
                self._consec <= 2 * self.skip_max + 1:
            if self._consec == self.skip_max + 1:
                self._rewarm_left = self.rewarm_steps
                self._set_governor(True)
                action = "rewarm"
            else:
                action = "skip"  # give the fresh ramp a chance
        else:
            action = "rollback"
        if action == "skip":
            _bump("skips")
        elif action == "rewarm":
            _bump("rewarms")
            _bump("skips")  # the anomalous batch itself is still skipped
        _telemetry_action(action, self._step)
        self.history.append((action, self._step, self.clock()))
        return action

    def lr_mult(self) -> float:
        """Re-warm ramp multiplier: rewarm_factor right after the
        trigger, back to 1.0 once rewarm_steps clean steps applied."""
        if self._rewarm_left <= 0 or self.rewarm_steps <= 0:
            return 1.0
        frac = 1.0 - self._rewarm_left / float(self.rewarm_steps)
        return self.rewarm_factor + (1.0 - self.rewarm_factor) * frac

    def _set_governor(self, on: bool) -> None:
        global _governor
        _governor = self if on else (None if _governor is self else
                                     _governor)

    # -- last-good retention ring ------------------------------------------
    def snapshot_due(self) -> bool:
        """True on the steps Module.fit should capture a ring snapshot
        (step 0 — before any update — always qualifies, so a rollback
        target exists from the first batch)."""
        return (self._step % max(1, self.snapshot_every)) == 0

    def offer_snapshot(self, capture: Callable[[], dict],
                       force: bool = False) -> bool:
        """Capture-and-retain when a snapshot is due; ``capture`` is
        only invoked if so (it copies params — not free).  ``force``
        overrides the cadence (fit forces one at each epoch start so a
        rollback target always exists inside the current epoch); never
        while anomalies are live, and at most one snapshot per observed
        step — a caller whose path never feeds :meth:`observe` gets
        exactly one snapshot, not one per batch."""
        if self._consec != 0:
            return False
        if self._step == self._last_snap_step and self._ring:
            return False
        if not (force or self.snapshot_due()):
            return False
        self._last_snap_step = self._step
        self._ring.append((self._step, capture()))
        del self._ring[:-self.ring_size]
        _bump("snapshots")
        return True

    def rollback_target(self, match: Optional[Callable[[dict], bool]]
                        = None) -> Optional[Tuple[int, dict]]:
        """Newest retained (step, snapshot) whose snapshot satisfies
        ``match`` (fit restricts to the current epoch — replaying across
        an epoch boundary would re-apply the previous epoch's tail), or
        None (fit then falls back to aborting)."""
        for step, snap in reversed(self._ring):
            if match is None or match(snap):
                return (step, snap)
        return None

    def note_rollback(self, to_step: Optional[int] = None) -> None:
        """Account one rollback: counters, anomaly event, flight-recorder
        postmortem (the evidence of WHY we rolled back — the last spans,
        events and metric values before the anomaly).  Raises
        :class:`GuardianAbort` past the budget."""
        self._rollbacks += 1
        self._consec = 0
        self._rewarm_left = 0
        self._set_governor(False)
        _bump("rollbacks")
        from . import telemetry as _tm

        if _tm.enabled():
            _tm.log_event("guardian_rollback", step=self._step,
                          to_step=to_step, count=self._rollbacks)
            _tm.flight_recorder.dump("guardian-rollback",
                                     extra={"step": self._step,
                                            "to_step": to_step})
        if self._rollbacks > self.rollback_max:
            raise GuardianAbort(
                "guardian rolled back %d times (budget %d): the anomaly "
                "is not transient — inspect the flight-recorder "
                "postmortem and the data/hardware under this run"
                % (self._rollbacks, self.rollback_max))

    # the detector state (median windows, consecutive count) is NOT
    # rolled back with the params: the anomalies it saw were real, and
    # the rollback budget must keep counting across replays

    def stats(self) -> dict:
        return {"step": self._step, "rollbacks": self._rollbacks,
                "ring": [s for s, _ in self._ring],
                "consecutive_anomalies": self._consec,
                "rewarm_left": self._rewarm_left,
                "lr_mult": self.lr_mult()}

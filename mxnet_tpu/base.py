"""Foundation utilities: errors, env-var config registry, dtype helpers.

TPU-native equivalent of the reference's dmlc-core portability layer
(logging / GetEnv / Parameter<T>) consumed throughout
/root/reference/src (e.g. src/engine/threaded_engine_perdevice.cc:34-46).
Here the config surface is a single typed env registry; per-op params live
in ops/param.py.
"""
from __future__ import annotations

import os
import logging
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = [
    "MXNetError",
    "env",
    "register_env",
    "list_env",
    "string_types",
    "numeric_types",
    "mx_real_t",
    "mx_uint",
    "_Null",
]

string_types = (str,)
numeric_types = (float, int, np.generic)
mx_real_t = np.float32
mx_uint = int


class _NullType:
    """Placeholder for unset keyword arguments (mirrors mxnet.base._Null)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "_Null"

    def __bool__(self):
        return False


_Null = _NullType()


class MXNetError(Exception):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


# ---------------------------------------------------------------------------
# Env-var config registry — the runtime config mechanism for the core, the
# analogue of dmlc::GetEnv usage cataloged in
# /root/reference/docs/how_to/env_var.md:1-100.
# ---------------------------------------------------------------------------

_ENV_REGISTRY: Dict[str, Dict[str, Any]] = {}


def register_env(name: str, default: Any, typ: Callable = str, doc: str = "") -> None:
    _ENV_REGISTRY[name] = {"default": default, "type": typ, "doc": doc}


def env(name: str, default: Optional[Any] = None, typ: Optional[Callable] = None) -> Any:
    """Read a typed environment variable, falling back to registered default."""
    spec = _ENV_REGISTRY.get(name)
    if spec is not None:
        if default is None:
            default = spec["default"]
        if typ is None:
            typ = spec["type"]
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None or typ is str:
        return raw
    if typ is bool:
        return raw.lower() not in ("0", "false", "")
    return typ(raw)


def list_env() -> Dict[str, Dict[str, Any]]:
    return dict(_ENV_REGISTRY)


# Canonical runtime knobs (docs/how_to/env_var.md parity, TPU semantics).
register_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice", str,
             "Engine facade mode: ThreadedEnginePerDevice (async JAX dispatch) "
             "or NaiveEngine (synchronous, blocks after every op; debug).")
register_env("MXNET_EXEC_BULK_EXEC_INFERENCE", 1, int,
             "Jit whole inference graphs (XLA fusion analogue of bulk-exec).")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", 1, int,
             "Jit whole training step.")
register_env("MXNET_BACKWARD_DO_MIRROR", 0, int,
             "Enable rematerialisation (jax.checkpoint) in the backward pass.")
register_env("MXNET_PROFILER_AUTOSTART", 0, int, "Start profiler at import.")
register_env("MXNET_PROFILER_MODE", 0, int, "0: symbolic only, 1: all ops.")
register_env("MXNET_CPU_WORKER_NTHREADS", 1, int, "Host worker threads for IO.")
register_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000 * 1000, int,
             "Threshold above which a kvstore value is sharded across servers.")
register_env("MXNET_DEFAULT_DTYPE", "float32", str,
             "Default array dtype; set bfloat16 for TPU-preferred compute.")


_LOGGER = None


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        _LOGGER = logging.getLogger("mxnet_tpu")
    return _LOGGER


def check_call(ret: Any) -> Any:
    """Parity shim for mxnet.base.check_call — errors raise MXNetError directly."""
    return ret

"""``mx.contrib.sym`` — contrib ops under their reference short names.

Parity: /root/reference/python/mxnet/contrib/symbol.py (the reference
codegen registers ``_contrib_Foo`` ops into the contrib module as ``Foo``).
"""
from .. import symbol as _symbol
from ._export import populate as _populate

__all__ = []

_populate(globals(), _symbol, __all__)

"""``mx.contrib.nd`` — imperative contrib ops under their reference short
names (parity: /root/reference/python/mxnet/contrib/ndarray.py)."""
from .. import ndarray as _ndarray
from ._export import populate as _populate

__all__ = []

_populate(globals(), _ndarray, __all__)

"""Shared re-export helper for the contrib namespaces."""
from ..ops.registry import registered_ops as _registered_ops

_PREFIX = "_contrib_"


def populate(namespace, source_module, all_list):
    """Bind every registered ``_contrib_*`` op from ``source_module`` into
    ``namespace`` under its reference short name (MultiBoxPrior, fft, ...)."""
    for name in _registered_ops():
        if not name.startswith(_PREFIX):
            continue
        short = name[len(_PREFIX):]
        fn = getattr(source_module, name, None)
        if fn is None or short in namespace:
            continue
        namespace[short] = fn
        all_list.append(short)

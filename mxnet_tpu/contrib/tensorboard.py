"""TensorBoard logging callback.

Parity: /root/reference/python/mxnet/contrib/tensorboard.py:8
(``LogMetricsCallback`` writing eval metrics as TensorBoard scalars).
Backed by ``torch.utils.tensorboard`` (pure event-file writer; no torch
compute involved); if that import is unavailable the callback degrades to a
JSONL scalar log in the same directory so training never breaks on a
logging dependency.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Log metrics at batch/epoch end to TensorBoard.

    Use as ``batch_end_callback`` or ``eval_end_callback`` in
    ``Module.fit`` — the callback reads ``param.eval_metric`` like
    ``Speedometer`` does (callback.py).
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        os.makedirs(logging_dir, exist_ok=True)
        try:
            from torch.utils.tensorboard import SummaryWriter

            self.summary_writer = SummaryWriter(logging_dir)
            self._jsonl = None
        except Exception:
            self.summary_writer = None
            self._jsonl = open(
                os.path.join(logging_dir, "scalars.jsonl"), "a")

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        names, values = self._name_values(param.eval_metric)
        for name, value in zip(names, values):
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            if self.summary_writer is not None:
                # SummaryWriter flushes on its own cadence; no per-batch
                # flush in the training hot path
                self.summary_writer.add_scalar(name, value, self.step)
            else:
                self._jsonl.write(json.dumps(
                    {"tag": name, "value": float(value), "step": self.step,
                     "wall_time": time.time()}) + "\n")
                self._jsonl.flush()

    @staticmethod
    def _name_values(metric):
        pairs = metric.get_name_value()
        return [p[0] for p in pairs], [p[1] for p in pairs]

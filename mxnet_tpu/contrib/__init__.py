"""``mx.contrib`` — experimental-op namespaces + TensorBoard callback.

Parity: /root/reference/python/mxnet/contrib/{__init__,symbol,ndarray,
tensorboard}.py.  Reference user scripts spell contrib ops as
``mx.contrib.sym.MultiBoxPrior(...)`` / ``mx.contrib.nd.fft(...)``; the
registry stores them under their C-registration names (``_contrib_*``),
and these modules re-export every ``_contrib_`` op under its short name.
"""
from . import ndarray
from . import symbol
from . import tensorboard

# reference aliases (contrib/__init__.py re-exports symbol as sym, ndarray
# as nd)
sym = symbol
nd = ndarray

__all__ = ["symbol", "ndarray", "sym", "nd", "tensorboard"]

"""Executor — binds a Symbol to devices and runs it.

TPU-native redesign of the reference GraphExecutor
(/root/reference/src/executor/graph_executor.cc:322-676 and
include/mxnet/executor.h).  Where the reference runs nnvm passes (Gradient,
PlanMemory, AttachOpExecs) and pushes one engine op per node, here the whole
graph lowers to ONE pure JAX function that XLA fuses and schedules — the
"bulk exec" of the reference (InitOpSegs, graph_executor.cc:678) taken to its
logical conclusion.  Autodiff (the Gradient pass + ``_backward_*`` ops) is
``jax.vjp``; memory planning/in-place sharing is XLA buffer assignment +
donation; ``MXNET_BACKWARD_DO_MIRROR`` maps to ``jax.checkpoint``.

Semantics kept from the reference:
  * ``grad_req`` in {write, add, null} per argument (kAddTo accumulation —
    the DetectInplaceAddTo pass — is functional accumulation here),
  * auxiliary states (BatchNorm moving stats) updated on training forward,
  * ``backward(out_grads)`` head gradients; loss ops ignore them via their
    custom vjps,
  * monitor callback surface (SetMonitorCallback, graph_executor.cc:69).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError, env
from .context import Context
from .ops import OpContext
from . import profiler as _prof
from . import random as _random

__all__ = ["Executor"]


class _GraphPlan:
    """Static lowering plan for a symbol: topo order, entry wiring, aux and
    stochastic bookkeeping.  Shared across executors binding the same symbol
    object (the analogue of shared_exec memory sharing in bucketing)."""

    def __init__(self, symbol):
        from .symbol import _topo_sort

        self.symbol = symbol
        self.nodes = _topo_sort(symbol._outputs)
        self.arg_names = [n.name for n in self.nodes if n.is_variable]
        self.aux_names: List[str] = []
        for n in self.nodes:
            self.aux_names.extend(n.aux_names())
        self.stochastic_nodes = [
            n for n in self.nodes if n.op is not None and n.op.stochastic]
        self.output_entries = [(id(node), idx) for node, idx in symbol._outputs]
        self.output_names = symbol.list_outputs()
        self._fingerprint = None

    def fingerprint(self) -> str:
        """Content hash of the graph (serialized symbol) — the
        process-independent half of a persistent compile-cache key."""
        if self._fingerprint is None:
            import hashlib

            self._fingerprint = hashlib.sha256(
                self.symbol.tojson().encode()).hexdigest()[:16]
        return self._fingerprint

    def placement_map(self, group2ctx):
        """Node-id → jax.Device from ``__ctx_group__`` attrs (reference:
        nnvm PlaceDevice pass + _CrossDeviceCopy splicing,
        src/executor/graph_executor.cc:230-320; here the cross-device copy
        is a jax.device_put compiled into the jitted graph)."""
        if not group2ctx:
            return {}
        placement = {}
        for n in self.nodes:
            if n.is_variable:
                continue
            # AttrScope stores the plain key; reference JSON may carry the
            # C-API-mangled "__ctx_group__" form — accept both
            group = None
            for store in (n.attr_dict, n.attrs):
                group = store.get("ctx_group") or store.get("__ctx_group__")
                if group:
                    break
            if group and group in group2ctx:
                placement[id(n)] = group2ctx[group].jax_device()
        return placement

    def run(self, args: Dict[str, Any], aux: Dict[str, Any], rng,
            is_train: bool, want_internals: bool = False, placement=None):
        """Execute the graph as a pure function of (args, aux, rng)."""
        import jax

        vals: Dict[tuple, Any] = {}
        new_aux: Dict[str, Any] = {}
        n_st = len(self.stochastic_nodes)
        keys = {}
        if n_st and rng is not None:
            subkeys = jax.random.split(rng, n_st)
            keys = {id(n): subkeys[i] for i, n in enumerate(self.stochastic_nodes)}
        for n in self.nodes:
            if n.is_variable:
                if n.name not in args:
                    raise MXNetError("missing argument %r" % n.name)
                vals[(id(n), 0)] = args[n.name]
                continue
            ins = [vals[(id(p), idx)] for p, idx in n.inputs]
            aux_in = tuple(aux[a] for a in n.aux_names())
            opctx = OpContext(is_train=is_train, rng=keys.get(id(n)))
            if placement and id(n) in placement:
                dev = placement[id(n)]
                ins = [jax.device_put(x, dev) for x in ins]
            outs, aux_out = n.op.apply(opctx, n.attrs, ins, aux_in)
            for i, o in enumerate(outs):
                vals[(id(n), i)] = o
            for aname, a in zip(n.aux_names(), aux_out):
                new_aux[aname] = a
        outputs = [vals[e] for e in self.output_entries]
        if want_internals:
            internals = {}
            for n in self.nodes:
                if n.is_variable:
                    continue
                for i in range(n.num_outputs()):
                    oname = n.op.output_names(n.attrs, n.name)[i]
                    internals[oname] = vals[(id(n), i)]
            return outputs, new_aux, internals
        return outputs, new_aux


class Executor:
    def __init__(self, symbol, ctx: Context, args, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None,
                 shared_exec: Optional["Executor"] = None,
                 compute_dtype=None, cast_exclude=()):
        from . import ndarray as nd

        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._group2ctx = group2ctx or {}
        # mixed precision: float32 args are cast to compute_dtype (bf16 on
        # TPU) inside the traced step; master params/grads/aux stay float32.
        # cast_exclude holds names that must keep full precision (labels —
        # bf16 cannot represent class ids > 256 exactly).
        self._compute_dtype = compute_dtype
        self._cast_exclude = frozenset(cast_exclude)
        if shared_exec is not None and shared_exec._symbol is symbol:
            self._plan = shared_exec._plan
        else:
            self._plan = _GraphPlan(symbol)
        plan = self._plan

        # ---- arguments -------------------------------------------------
        if isinstance(args, dict):
            self.arg_dict = {k: self._as_nd(v) for k, v in args.items()}
            missing = [a for a in plan.arg_names if a not in self.arg_dict]
            if missing:
                raise MXNetError("bind missing arguments: %s" % missing)
        else:
            args = list(args)
            if len(args) != len(plan.arg_names):
                raise MXNetError(
                    "bind expects %d args, got %d" % (len(plan.arg_names), len(args)))
            self.arg_dict = {n: self._as_nd(a) for n, a in zip(plan.arg_names, args)}
        self.arg_arrays = [self.arg_dict[n] for n in plan.arg_names]

        # ---- gradients -------------------------------------------------
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in plan.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(plan.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in plan.arg_names}
        # inputs an op declares non-differentiable (labels, indices)
        for n in plan.nodes:
            if n.is_variable or not n.op.no_grad_inputs:
                continue
            in_names = n.op.input_names(n.attrs)
            for iname, (p, _) in zip(in_names, n.inputs):
                if iname in n.op.no_grad_inputs and p.is_variable:
                    self._grad_req[p.name] = "null"
        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, dict):
            self.grad_dict = {k: self._as_nd(v) for k, v in args_grad.items()}
        else:
            self.grad_dict = {
                n: self._as_nd(g) for n, g in zip(plan.arg_names, args_grad)
                if g is not None}
        for name in list(self.grad_dict):
            if self._grad_req.get(name, "null") == "null":
                del self.grad_dict[name]
        self.grad_arrays = [self.grad_dict.get(n) for n in plan.arg_names]

        # ---- aux states ------------------------------------------------
        if aux_states is None:
            aux_states = []
        if isinstance(aux_states, dict):
            self.aux_dict = {k: self._as_nd(v) for k, v in aux_states.items()}
        else:
            aux_states = list(aux_states)
            self.aux_dict = {n: self._as_nd(a)
                             for n, a in zip(plan.aux_names, aux_states)}
        for aname in plan.aux_names:
            if aname not in self.aux_dict:
                raise MXNetError("bind missing auxiliary state %r" % aname)
        self.aux_arrays = [self.aux_dict[n] for n in plan.aux_names]

        self._output_arrays: List = []
        self._monitor_callback = None
        self._jit_cache: Dict[Any, Any] = {}
        # compile-cache entry label for this executor's forwards ("fwd" by
        # default); specialized call sites (the generation decode step sets
        # "gen-step", its prefill "gen-prefill") override it so their
        # entries are both distinctly keyed and legible in
        # `compile_cache_admin.py ls`
        self._cache_kind = "fwd"
        # NaiveEngine parity: MXNET_ENGINE_TYPE=NaiveEngine disables jit and
        # synchronizes after every call (threaded_engine.h:329-337 debugging).
        self._naive = env("MXNET_ENGINE_TYPE") == "NaiveEngine"
        # graphs with Python-callback ops need host send/recv inside jit;
        # on backends without it (some tunneled TPU platforms) fall back to
        # eager execution so the graph still runs
        if not self._naive and any(
                n.op is not None and n.op.name in ("Custom", "_Native",
                                                   "_NDArray")
                for n in plan.nodes):
            from .operator import host_callbacks_supported

            if not host_callbacks_supported():
                import logging

                logging.warning(
                    "graph contains Python-callback ops but backend lacks "
                    "host-callback support under jit; executor runs eagerly")
                self._naive = True
        # model parallelism: ctx-group → device placement compiled into the
        # step (group2ctx was previously accepted but silently ignored)
        self._placement = plan.placement_map(self._group2ctx)
        # SPMD shardings (set_shardings): mesh + per-name PartitionSpecs.
        # XLA partitions every compiled step from the committed input
        # shardings — tensor parallelism needs no graph changes here.
        self._shard_mesh = None
        self._shard_specs: Dict[str, Any] = {}
        self._shard_fingerprint = None

    # ------------------------------------------------------------------
    def _as_nd(self, v):
        from . import ndarray as nd

        if isinstance(v, nd.NDArray):
            return v
        return nd.array(v, self._ctx)

    @property
    def outputs(self) -> List:
        return self._output_arrays

    @property
    def output_dict(self) -> Dict[str, Any]:
        return dict(zip(self._plan.output_names, self._output_arrays))

    # ------------------------------------------------------------------
    # compiled callables
    # ------------------------------------------------------------------
    def _cast_fn(self):
        """Build the traced mixed-precision cast over an args dict."""
        if self._compute_dtype is None:
            return lambda args: args
        import jax.numpy as jnp

        cdt = jnp.dtype(self._compute_dtype)
        exclude = self._cast_exclude

        def cast(args):
            out = {}
            for k, v in args.items():
                if k not in exclude and v.dtype == jnp.float32:
                    out[k] = v.astype(cdt)
                else:
                    out[k] = v
            return out

        return cast

    def _get_fwd(self, is_train: bool, internals: bool = False):
        import jax

        kind = self._cache_kind
        key = (kind, is_train, internals)
        if key not in self._jit_cache:
            plan = self._plan

            placement = self._placement
            cast = self._cast_fn()

            def fn(args, aux, rng):
                return plan.run(cast(args), aux, rng, is_train,
                                want_internals=internals, placement=placement)

            if self._naive:
                self._jit_cache[key] = fn
            else:
                from . import compile_cache as _cc

                self._jit_cache[key] = _cc.maybe_cached(
                    jax.jit(fn), kind, key, self)
        return self._jit_cache[key]

    def _get_fwd_bwd(self, is_train: bool, diff_names: tuple, add_names: tuple):
        import jax

        key = ("fwdbwd", is_train, diff_names, add_names)
        if key not in self._jit_cache:
            plan = self._plan
            remat = bool(env("MXNET_BACKWARD_DO_MIRROR", 0, int))
            placement = self._placement

            cast = self._cast_fn()

            def fn(diff_args, other_args, aux, rng, out_grads, old_grads):
                def f(d):
                    merged = dict(other_args)
                    merged.update(d)
                    outs, new_aux = plan.run(cast(merged), aux, rng, is_train,
                                             placement=placement)
                    return tuple(outs), new_aux

                f2 = jax.checkpoint(f) if remat else f
                primals, vjp_fn = jax.vjp(f2, diff_args)
                outs, new_aux = primals
                cts = tuple(
                    og if og is not None else jax.numpy.ones_like(o)
                    for o, og in zip(outs, out_grads))
                (grads,) = vjp_fn((cts, jax.tree_util.tree_map(
                    jax.numpy.zeros_like, new_aux)))
                for name in add_names:
                    grads[name] = grads[name] + old_grads[name]
                return list(outs), new_aux, grads

            if self._naive:
                self._jit_cache[key] = fn
            else:
                from . import compile_cache as _cc

                self._jit_cache[key] = _cc.maybe_cached(
                    jax.jit(fn), "fwdbwd", key, self)
        return self._jit_cache[key]

    # ------------------------------------------------------------------
    # fused train step (forward + backward + optimizer update)
    # ------------------------------------------------------------------
    @staticmethod
    def _unwrap_state(state):
        """Optimizer state (NDArray / tuple / None) → jax pytree."""
        from . import ndarray as nd

        if state is None:
            return None
        if isinstance(state, nd.NDArray):
            return state._data
        if isinstance(state, (list, tuple)):
            return tuple(Executor._unwrap_state(s) for s in state)
        return state

    @staticmethod
    def _rewrap_state(holder, new, ctx):
        """Write a new jax pytree back into the Updater's NDArray structure
        (buffer rebinding only — no device work)."""
        from . import ndarray as nd

        if holder is None or new is None:
            return holder if new is None else nd.NDArray(new, ctx)
        if isinstance(holder, nd.NDArray):
            holder._set(new)
            return holder
        if isinstance(holder, (list, tuple)):
            return tuple(Executor._rewrap_state(h, n, ctx)
                         for h, n in zip(holder, new))
        return new

    def _fused_shardings(self, diff_args, states, aux, other_args):
        """(in_shardings, out_shardings) pytrees for the fused step when a
        mesh is active: every named array pins its PartitionSpec, optimizer
        state leaves inherit their parameter's spec when like-shaped (else
        replicate), and the rng/scalar slots stay unconstrained.  Lowering
        the step under explicit shardings (rather than inferring from the
        committed inputs alone) makes the SPMD layout part of the program
        signature — reshard bugs fail at compile, not as silent copies."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._shard_mesh
        rep = NamedSharding(mesh, PartitionSpec())

        def ns(name):
            return NamedSharding(mesh,
                                 self._shard_specs.get(name, PartitionSpec()))

        def state_ns(name, sub):
            pshape = tuple(self.arg_dict[name].shape)

            def leaf(x):
                return ns(name) if tuple(x.shape) == pshape else rep

            return jax.tree_util.tree_map(leaf, sub)

        d = {k: ns(k) for k in diff_args}
        s = {k: state_ns(k, sub) for k, sub in states.items()}
        a = {k: ns(k) for k in aux}
        o = {k: ns(k) for k in other_args}
        in_s = (d, s, a, o, None, rep, None)
        # fifth slot: the step-guard verdict (ok, gnorm) — replicated scalars
        out_s = (None, a, d, s, rep)
        return in_s, out_s

    def _autotune_fused(self, stable_key, abstract_args, make_jit,
                        donate_allowed, env_remat):
        """Tuned {remat, donate} for this fused program, or None.  The
        record-mode loop lowers each remat x donation variant of the
        EXACT program about to run (same graph, same abstract args) and
        scores by the XLA-cost-analysis roofline.  Any failure degrades
        to the env-derived defaults."""
        if abstract_args is None:
            return None
        try:
            from . import autotune

            if not autotune.enabled():
                return None
            import jax

            sig = jax.tree_util.tree_map(
                lambda x: (tuple(x.shape), str(x.dtype)), abstract_args)
            key = {"graph": self._plan.fingerprint(),
                   "static": repr(stable_key),
                   "compute_dtype": str(self._compute_dtype),
                   "sig": repr(sig),
                   "remat_env": int(env_remat),
                   "donate_allowed": bool(donate_allowed)}

            def build(cand):
                return (make_jit(bool(cand["remat"]),
                                 bool(cand["donate"])), abstract_args)

            return autotune.get_or_tune(
                "fused_step", key,
                candidates=autotune.spaces.fused_step(donate_allowed),
                build_fn=build, default=None)
        except Exception:
            return None

    def _get_fused_step(self, key, update_infos, pure_update, needs_rng,
                        shardings=None, stable_key=None, abstract_args=None,
                        guard=False):
        """Jitted forward+backward+update with donated param/state/aux
        buffers.  This is the whole of the reference's per-batch engine
        traffic (GraphExecutor::Forward/Backward + the kvstore push/pull +
        fused optimizer kernels, model.py:88-116) as ONE XLA program — no
        host dispatch per parameter, buffers reused in place via donation.
        Under an active mesh, ``shardings`` = (in_shardings, out_shardings)
        lowers the single program SPMD-partitioned.

        With ``guard`` (the training guardian's step guard) the program
        also reduces ``isfinite`` over every gradient and output and
        gates the param/state/aux update on the verdict: a non-finite
        step is SKIPPED on device (old buffers selected) and the scalar
        verdict comes back as a fifth result — one fused all-reduce, no
        extra host round-trip.  Guard off returns a constant-true
        verdict, which XLA folds away."""
        import jax
        import jax.numpy as jnp

        if key not in self._jit_cache:
            plan = self._plan
            placement = self._placement
            env_remat = bool(env("MXNET_BACKWARD_DO_MIRROR", 0, int))
            cast = self._cast_fn()

            def make_fn(remat):
                def fn(diff_args, states, aux, other_args, rng, sc, opt_rng):
                    lr0, wd0, t = sc

                    def f(d):
                        merged = dict(other_args)
                        merged.update(d)
                        outs, new_aux = plan.run(cast(merged), aux, rng,
                                                 True, placement=placement)
                        return tuple(outs), new_aux

                    f2 = jax.checkpoint(f) if remat else f
                    primals, vjp_fn = jax.vjp(f2, diff_args)
                    outs, new_aux = primals
                    cts = tuple(jnp.ones_like(o) for o in outs)
                    (grads,) = vjp_fn((cts, jax.tree_util.tree_map(
                        jnp.zeros_like, new_aux)))
                    keys = {}
                    if needs_rng and opt_rng is not None:
                        subkeys = jax.random.split(opt_rng, len(update_infos))
                        keys = {name: subkeys[i]
                                for i, (name, _, _, _)
                                in enumerate(update_infos)}
                    new_params = {}
                    new_states = {}
                    for name, _idx, lmult, wmult in update_infos:
                        w, s = pure_update(
                            diff_args[name], grads[name], states[name],
                            lr0 * lmult, wd0 * wmult, t, keys.get(name))
                        new_params[name] = w
                        new_states[name] = s
                    if guard:
                        ok = jnp.bool_(True)
                        sq = jnp.float32(0)
                        for name, _idx, _, _ in update_infos:
                            g = grads[name]
                            ok &= jnp.all(jnp.isfinite(g))
                            sq += jnp.sum(jnp.square(
                                g.astype(jnp.float32)))
                        for o in outs:
                            if jnp.issubdtype(o.dtype, jnp.floating):
                                ok &= jnp.all(jnp.isfinite(o))
                        gnorm = jnp.sqrt(sq)
                        # the f32 norm overflowing is itself an anomaly:
                        # a single exponent bit-flip lands ~1e38 in a
                        # gradient, which is finite but squares to inf —
                        # catch it here, not N steps later in the spike
                        # detector
                        ok &= jnp.isfinite(gnorm)
                        # on-device skip: a poisoned batch leaves params,
                        # optimizer state and aux (BN stats) untouched
                        sel = lambda new, old: jnp.where(ok, new, old)
                        new_params = {k: sel(v, diff_args[k])
                                      for k, v in new_params.items()}
                        new_states = jax.tree_util.tree_map(
                            sel, new_states, states)
                        new_aux = jax.tree_util.tree_map(sel, new_aux, aux)
                    else:
                        ok = jnp.bool_(True)
                        gnorm = jnp.float32(0)
                    return (list(outs), new_aux, new_params, new_states,
                            (ok, gnorm))

                return fn

            if self._naive:
                self._jit_cache[key] = make_fn(env_remat)
            else:
                from . import compile_cache as _cc

                # Cache-eligible executables are built WITHOUT donation:
                # XLA's executable deserializer can mis-bind donated
                # (input-output aliased) arguments that share a shape, so
                # an entry compiled here must stay correct when another
                # process deserializes it.  The default (cache off) keeps
                # in-place buffer reuse.
                donate_allowed = not _cc.active()

                def make_jit(remat, donate_on):
                    donate = (0, 1, 2) if (donate_on and donate_allowed) \
                        else ()
                    fn = make_fn(remat)
                    if shardings is not None:
                        return jax.jit(fn, donate_argnums=donate,
                                       in_shardings=shardings[0],
                                       out_shardings=shardings[1])
                    return jax.jit(fn, donate_argnums=donate)

                remat, donate_on = env_remat, donate_allowed
                tuned = self._autotune_fused(stable_key, abstract_args,
                                             make_jit, donate_allowed,
                                             env_remat)
                if tuned is not None:
                    remat = bool(tuned.get("remat", remat))
                    donate_on = (bool(tuned.get("donate", donate_on))
                                 and donate_allowed)
                    self._fused_autotune = dict(tuned)
                jfn = make_jit(remat, donate_on)
                # the persistent key uses stable_key (no object ids) so a
                # fresh process — or a fresh optimizer instance with the
                # same hypers — maps to the same disk entry; donation and
                # remat change the compiled program, so they are part of
                # the key
                donate = (0, 1, 2) if (donate_on and donate_allowed) else ()
                if stable_key is not None:
                    stable_key = stable_key + (("donate", tuple(donate)),
                                               ("remat", int(remat)))
                self._jit_cache[key] = _cc.maybe_cached(
                    jfn, "fused", stable_key, self)
        return self._jit_cache[key]

    def fused_step(self, optimizer, updater, param_names):
        """Run one fused train step: loads nothing (inputs must already be in
        ``arg_dict``), updates params/states/aux in place, sets outputs.

        ``param_names`` gives the updater index space (position in list ==
        kvstore key, as Module wires idx2name).  Requires every param's
        grad_req to be 'write' or 'null' and an optimizer with
        ``pure_update``."""
        import numpy as _np
        from . import ndarray as nd
        from . import random as _random

        plan = self._plan
        infos = []
        for idx, name in enumerate(param_names):
            if self._grad_req.get(name, "null") == "null":
                continue
            if idx not in updater.states:
                updater.states[idx] = optimizer.create_state(
                    idx, self.arg_dict[name])
            # static per-param multipliers (scheduler lr stays traced)
            lmult = optimizer.lr_mult.get(idx, optimizer.lr_mult.get(
                optimizer.idx2name.get(idx, name), 1.0))
            wmult = optimizer.wd_mult.get(idx, optimizer.wd_mult.get(
                optimizer.idx2name.get(idx, name), 1.0))
            infos.append((name, idx, float(lmult), float(wmult)))
            optimizer._update_count(idx)

        t = optimizer.num_update
        lr0 = optimizer.lr_scheduler(t) if optimizer.lr_scheduler is not None \
            else optimizer.lr
        from . import guardian as _guardian

        if _guardian._governor is not None:
            # re-warm ramp: lr rides in as a traced scalar, so the ramp
            # never recompiles the fused program
            lr0 *= _guardian.current_lr_mult()
        sc = (_np.float32(lr0), _np.float32(optimizer.wd), _np.int32(t))

        diff_args = {}
        states = {}
        other_args = {}
        diff_set = {name for name, _, _, _ in infos}
        for k, v in self.arg_dict.items():
            (diff_args if k in diff_set else other_args)[k] = v._data
        for name, idx, _, _ in infos:
            states[name] = self._unwrap_state(updater.states[idx])
        aux = {k: v._data for k, v in self.aux_dict.items()}

        # donation requires distinct buffers; NDArray.copy() shares the
        # immutable jax array (e.g. DCASGD's previous-weight state right
        # after create_state), so break aliases with a real copy once
        import jax

        seen = {id(v) for v in diff_args.values()}

        def _dedupe(leaf):
            if leaf is None:
                return None
            if id(leaf) in seen:
                return jax.numpy.array(leaf, copy=True)
            seen.add(id(leaf))
            return leaf

        states = jax.tree_util.tree_map(_dedupe, states)
        aux = {k: _dedupe(v) for k, v in aux.items()}
        rng = _random.next_key() if plan.stochastic_nodes else None
        opt_rng = _random.next_key() if optimizer.needs_rng else None

        # hyperparameters are baked into the trace, so fingerprint every
        # scalar hyper (momentum, betas, rho, ...) — not just identity —
        # excluding per-step bookkeeping and the traced lr/wd scalars
        hypers = tuple(sorted(
            (k, float(v)) for k, v in vars(optimizer).items()
            if isinstance(v, (int, float, bool)) and
            k not in ("num_update", "begin_num_update", "lr", "wd")))
        # the guardian's step guard changes the compiled program (isfinite
        # reduction + gated update), so it discriminates both cache keys
        from . import guardian as _guardian

        guard = _guardian.enabled()
        key = ("fused", tuple(infos), id(optimizer), type(optimizer).__name__,
               hypers, float(optimizer.rescale_grad),
               float(optimizer.clip_gradient or 0.0),
               self._shard_fingerprint, guard)
        # the same key with every process-unstable part (object ids, shard
        # fingerprint — the compile cache derives a stable one from the
        # mesh itself) removed: what the persistent compile cache keys on
        stable_key = ("fused", tuple(infos), type(optimizer).__name__,
                      hypers, float(optimizer.rescale_grad),
                      float(optimizer.clip_gradient or 0.0),
                      bool(optimizer.needs_rng), ("guard", int(guard)))
        first_build = key not in self._jit_cache
        shardings = None
        abstract_args = None
        if first_build and not self._naive:
            if self._shard_mesh is not None:
                shardings = self._fused_shardings(diff_args, states, aux,
                                                  other_args)
            # abstract arg signature of the fused call: the autotuner
            # lowers candidate variants against it, and perf_probe reuses
            # it (via _fused_introspect) to lower the exact same program
            abstract_args = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                (diff_args, states, aux, other_args, rng, sc, opt_rng))
        fn = self._get_fused_step(key, tuple(infos), optimizer.pure_update,
                                  optimizer.needs_rng, shardings,
                                  stable_key=stable_key,
                                  abstract_args=abstract_args,
                                  guard=guard)
        if first_build and not self._naive:
            # introspection hook (compile-miss path only — zero per-step
            # cost), so tools/perf_probe.py can lower/compile the exact
            # same program and read XLA cost analysis / HLO without
            # re-deriving the arg packing
            self._fused_introspect = (fn, abstract_args)
            # consumed by telemetry.StepMonitor (Module.update): one XLA
            # cost analysis per new executable, never per step
            self._fused_new_compile = True
        with _prof.Frame("Executor.fused_step", "exec"):
            outs, new_aux, new_params, new_states, verdict = fn(
                diff_args, states, aux, other_args, rng, sc, opt_rng)
        # the on-device (ok, grad_norm) verdict: still device scalars —
        # the guardian reads them where the step already syncs (metric
        # update), so the guard adds no host round-trip of its own
        self._guard_verdict = verdict if guard else None
        if first_build and not self._naive:
            # when the compile cache primed this executable, XLA's cost
            # analysis rode along (entry meta on hits, read once from the
            # fresh Compiled on misses) — StepMonitor consumes this instead
            # of re-lowering+re-compiling the program
            self._fused_cost_info = getattr(fn, "cost_info", None)

        for name, idx, _, _ in infos:
            self.arg_dict[name]._set(new_params[name])
            updater.states[idx] = self._rewrap_state(
                updater.states[idx], new_states[name], self._ctx)
        for k, v in new_aux.items():
            self.aux_dict[k]._set(v)
        self._output_arrays = [nd.NDArray(o, self._ctx) for o in outs]
        if self._naive:
            for o in self._output_arrays:
                o.wait_to_read()
        return self._output_arrays

    # ------------------------------------------------------------------
    # execution API
    # ------------------------------------------------------------------
    def set_shardings(self, mesh, arg_specs=None, aux_specs=None):
        """Tensor/data-parallel placement through the product executor.

        ``mesh`` is a ``jax.sharding.Mesh``; ``arg_specs``/``aux_specs`` map
        argument/aux names to ``PartitionSpec``s (unnamed arrays are
        replicated).  Every bound arg, gradient buffer and aux state is
        committed onto the mesh; XLA then partitions each compiled step
        (forward / backward / fused) over it, inserting the collectives —
        e.g. a FullyConnected weight sharded on a 'model' axis runs as a
        partitioned matmul with the activation all-gather/psum compiled in.
        TPU-native replacement for the reference's multi-device executor
        split (graph_executor.cc device placement + kvstore comm); batch
        inputs fed later via ``forward(**kwargs)`` keep their spec."""
        from jax.sharding import PartitionSpec

        self._shard_mesh = mesh
        self._shard_specs = dict(arg_specs or {})
        if aux_specs:
            self._shard_specs.update(aux_specs)
        # jit-cache discriminator: a later set_shardings with different
        # specs must re-lower the fused step instead of reusing a program
        # compiled for the old layout
        self._shard_fingerprint = (
            id(mesh), tuple(sorted((k, str(v))
                                   for k, v in self._shard_specs.items())))

        known = set(self.arg_dict) | set(self.aux_dict) | set(self.grad_dict)
        unknown = sorted(set(self._shard_specs) - known)
        if unknown:
            raise MXNetError(
                "set_shardings: specs name no bound argument/aux: %s"
                % unknown)

        from .sharding import place as _place

        def put(arrs):
            for name, arr in arrs.items():
                spec = self._shard_specs.get(name, PartitionSpec())
                arr._set(_place(arr._data, mesh, spec))

        put(self.arg_dict)
        put(self.aux_dict)
        put(self.grad_dict)

    def _write_arg(self, name, value, aux=False):
        """The single write path for bound arrays: one host→device
        transfer, committed straight onto the mesh when shardings are
        active (so a caller-side update never silently drops a spec or
        double-copies the batch)."""
        from . import ndarray as nd

        target = (self.aux_dict if aux else self.arg_dict)[name]
        if self._shard_mesh is None:
            target[:] = value if not isinstance(value, np.ndarray) else \
                nd.array(value, self._ctx)
            return
        from jax.sharding import PartitionSpec

        from .sharding import place as _place

        v = value._data if isinstance(value, nd.NDArray) else \
            np.asarray(value, dtype=target.dtype)
        spec = self._shard_specs.get(name, PartitionSpec())
        target._set(_place(v, self._shard_mesh, spec))

    def forward(self, is_train: bool = False, **kwargs):
        from . import ndarray as nd

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown forward argument %r" % k)
            self._write_arg(k, v)
        args = {k: v._data for k, v in self.arg_dict.items()}
        aux = {k: v._data for k, v in self.aux_dict.items()}
        rng = _random.next_key() if self._plan.stochastic_nodes else None
        self._last_rng = rng
        with _prof.Frame("Executor.forward", "exec"):
            if self._monitor_callback is not None:
                outs, new_aux, internals = self._get_fwd(is_train, True)(
                    args, aux, rng)
                for name, arr in internals.items():
                    self._monitor_callback(name, nd.NDArray(arr, self._ctx))
            else:
                outs, new_aux = self._get_fwd(is_train, False)(args, aux, rng)
        if is_train:
            for k, v in new_aux.items():
                self.aux_dict[k]._set(v)
        self._output_arrays = [nd.NDArray(o, self._ctx) for o in outs]
        if self._naive:
            for o in self._output_arrays:
                o.wait_to_read()
        return self._output_arrays

    def backward(self, out_grads=None, is_train: bool = True):
        self._forward_backward(out_grads, is_train=is_train, update_aux=False)

    def forward_backward(self, out_grads=None, is_train: bool = True, **kwargs):
        """Fused train step (one XLA program): forward + grads + aux update.
        The hot path used by Module.fit."""
        from . import ndarray as nd

        for k, v in kwargs.items():
            self._write_arg(k, v)
        self._last_rng = _random.next_key() if self._plan.stochastic_nodes else None
        self._forward_backward(out_grads, is_train=is_train, update_aux=True,
                               set_outputs=True)
        return self._output_arrays

    def _forward_backward(self, out_grads, is_train: bool, update_aux: bool,
                          set_outputs: bool = False):
        from . import ndarray as nd

        plan = self._plan
        diff_names = tuple(sorted(
            n for n in plan.arg_names if self._grad_req.get(n, "null") != "null"))
        if not diff_names:
            if set_outputs:
                self.forward(is_train=is_train)
            return
        add_names = tuple(sorted(
            n for n in diff_names if self._grad_req[n] == "add"))
        # grad_req='add' accumulates into the existing gradient array; if the
        # user bound none, start the accumulator at zero instead of failing
        # with a KeyError inside the traced function.
        for name in add_names:
            if name not in self.grad_dict:
                src = self.arg_dict[name]
                self.grad_dict[name] = nd.zeros(src.shape, self._ctx,
                                                dtype=src.dtype)
        args = {k: v._data for k, v in self.arg_dict.items()}
        aux = {k: v._data for k, v in self.aux_dict.items()}
        diff_args = {k: args[k] for k in diff_names}
        other_args = {k: v for k, v in args.items() if k not in diff_names}
        rng = getattr(self, "_last_rng", None)
        if rng is None and plan.stochastic_nodes:
            rng = _random.next_key()
        if out_grads is None:
            ogs = [None] * len(plan.output_entries)
        elif isinstance(out_grads, (list, tuple)):
            ogs = [g._data if isinstance(g, nd.NDArray) else g for g in out_grads]
        else:
            ogs = [out_grads._data if isinstance(out_grads, nd.NDArray) else out_grads]
        old_grads = {k: self.grad_dict[k]._data for k in add_names
                     if k in self.grad_dict}
        fn = self._get_fwd_bwd(is_train, diff_names, add_names)
        with _prof.Frame("Executor.forward_backward", "exec"):
            outs, new_aux, grads = fn(diff_args, other_args, aux, rng, ogs,
                                      old_grads)
        for name in diff_names:
            if name in self.grad_dict:
                self.grad_dict[name]._set(grads[name])
            else:
                self.grad_dict[name] = nd.NDArray(grads[name], self._ctx)
        self.grad_arrays = [self.grad_dict.get(n) for n in plan.arg_names]
        if update_aux:
            for k, v in new_aux.items():
                self.aux_dict[k]._set(v)
        if set_outputs:
            self._output_arrays = [nd.NDArray(o, self._ctx) for o in outs]
        if self._naive:
            for g in self.grad_dict.values():
                g.wait_to_read()

    # ------------------------------------------------------------------
    # parameter management
    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self._write_arg(name, arr)
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" not in arguments" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self._write_arg(name, arr, aux=True)
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" not in aux states" % name)

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to new input shapes (sharing the plan;
        XLA compile cache keyed by shapes plays the role of the reference's
        shared memory pool, graph_executor.cc:483-529)."""
        from . import ndarray as nd

        new_shapes = dict(kwargs)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes for reshape")
        args = {}
        for name, shape in zip(self._plan.arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if tuple(cur.shape) == tuple(shape):
                args[name] = cur
            else:
                args[name] = nd.zeros(shape, self._ctx, dtype=cur.dtype)
        aux = {}
        for name, shape in zip(self._plan.aux_names, aux_shapes):
            cur = self.aux_dict[name]
            aux[name] = cur if tuple(cur.shape) == tuple(shape) else \
                nd.zeros(shape, self._ctx, dtype=cur.dtype)
        grads = {n: nd.zeros(args[n].shape, self._ctx, dtype=args[n].dtype)
                 for n in self.grad_dict}
        return Executor(self._symbol, self._ctx, args, grads or None,
                        self._grad_req, aux, group2ctx=self._group2ctx,
                        shared_exec=self)

    def debug_str(self) -> str:
        lines = ["Symbol outputs: %s" % ", ".join(self._plan.output_names)]
        for n in self._plan.nodes:
            if n.is_variable:
                lines.append("Variable:%s" % n.name)
            else:
                lines.append("Op:%s, Name=%s" % (n.op.name, n.name))
        total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                    for a in self.arg_dict.values())
        lines.append("Total %d MB allocated for args" % (total >> 20))
        return "\n".join(lines)

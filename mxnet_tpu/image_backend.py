"""Host-side image decode backend.

The reference uses OpenCV (src/io/image_io.cc, iter_image_recordio.cc).
Here decoding happens on host CPU via PIL (fallback: raw numpy for uncompressed
payloads); decoded uint8 HWC arrays are then fed to the device pipeline.
"""
from __future__ import annotations

import io

import numpy as np

__all__ = ["decode_image", "encode_image", "resize_image", "HAVE_PIL"]

try:
    from PIL import Image

    HAVE_PIL = True
except ImportError:  # pragma: no cover
    HAVE_PIL = False


def decode_image(buf, channels: int = 3) -> np.ndarray:
    """Decode an encoded image buffer to HWC uint8 (RGB order, matching the
    reference's to_rgb=True default in imdecode)."""
    if isinstance(buf, np.ndarray):
        buf = buf.tobytes()
    if not HAVE_PIL:
        raise RuntimeError("No image decode backend available (PIL missing)")
    img = Image.open(io.BytesIO(buf))
    if channels == 3:
        img = img.convert("RGB")
    elif channels == 1:
        img = img.convert("L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def encode_image(arr: np.ndarray, img_fmt: str = ".jpg", quality: int = 95) -> bytes:
    """Encode an HWC uint8 array to JPEG/PNG bytes (reference pack_img uses
    OpenCV imencode)."""
    if not HAVE_PIL:
        raise RuntimeError("No image encode backend available (PIL missing)")
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    img = Image.fromarray(arr[..., 0] if arr.ndim == 3 and arr.shape[-1] == 1
                          else arr)
    out = io.BytesIO()
    if fmt == "JPEG":
        img.save(out, fmt, quality=quality)
    else:
        img.save(out, fmt)
    return out.getvalue()


def resize_image(arr: np.ndarray, w: int, h: int, interp: int = 1) -> np.ndarray:
    """Resize HWC preserving dtype: uint8 goes through PIL directly; float
    images are resized per-channel in 'F' mode (PIL has no float RGB mode) —
    no wrapping casts."""
    if not HAVE_PIL:
        raise RuntimeError("No image resize backend available (PIL missing)")
    interp_map = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                  3: Image.NEAREST, 4: Image.LANCZOS}
    mode = interp_map.get(interp, Image.BILINEAR)
    if np.issubdtype(arr.dtype, np.floating):
        chans = [np.asarray(Image.fromarray(
            arr[:, :, c].astype(np.float32), mode="F").resize((w, h), mode))
            for c in range(arr.shape[-1])]
        return np.stack(chans, axis=-1).astype(arr.dtype, copy=False)
    img = Image.fromarray(arr.squeeze() if arr.shape[-1] == 1 else arr)
    img = img.resize((w, h), mode)
    out = np.asarray(img)
    if out.ndim == 2:
        out = out[:, :, None]
    return out

"""Library-location helper (reference python/mxnet/libinfo.py
find_lib_path). The compute path here is JAX/XLA (no libmxnet.so); the
native runtime pieces are ``libmxtpu.so`` (RecordIO/decode) and
``libmxtpu_capi.so`` (the C ABI), both living next to the package."""
from __future__ import annotations

import os

__version__ = "0.9.5-tpu"


def find_lib_path():
    """Paths of the native libraries that exist on disk (build with
    ``make -C src all``); empty list when none are built yet."""
    pkg_dir = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    candidates = [os.path.join(pkg_dir, name)
                  for name in ("libmxtpu.so", "libmxtpu_capi.so")]
    return [p for p in candidates if os.path.exists(p)]

"""Attribute scoping for symbols (parity: python/mxnet/attribute.py:7).

``AttrScope`` attaches string attributes (e.g. ``__ctx_group__`` for model
parallelism, ``__force_mirroring__`` for remat, ``__shard__`` for the
TPU-native sharding annotations) to every symbol created inside the scope.
"""
from __future__ import annotations

import threading

from .base import string_types

__all__ = ["AttrScope"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, string_types):
                raise ValueError("Attributes need to be string")
        self._old_scope = None
        self._attr = kwargs

    def get(self, attr):
        """Merge user-supplied attrs over the scope attrs."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        AttrScope._current.value = self._old_scope

    @classmethod
    def current(cls) -> "AttrScope":
        if not hasattr(cls._current, "value"):
            cls._current.value = AttrScope()
        return cls._current.value

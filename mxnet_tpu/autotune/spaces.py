"""Declared search spaces, one per tunable site.

Each function enumerates the candidate configs the Tuner scores — small,
hand-declared grids (the TVM "search once per workload" loop, not an
open-ended schedule search).  Enumeration order is deterministic and
candidates are deduped by their EFFECTIVE config (e.g. flash block
requests that clamp to the same tile), so scoring never pays twice for
the same program.
"""
from __future__ import annotations

from typing import List, Sequence

# block grid the flash kernels accept; PERF.md's A/B sweeps ran exactly
# these sizes (the 4.7x MFU spread lives inside this grid)
FLASH_BLOCK_CHOICES = (128, 256, 512, 1024)

GEN_PAGE_SIZE_CHOICES = (8, 16, 32, 64)


def flash_blocks(seq_q: int, seq_k: int) -> List[dict]:
    """block_q x block_k grid, deduped by the clamped tile actually
    staged (``_pick_block`` halves a request until it divides the
    sequence)."""
    from ..ops.attention import _pick_block

    seen, out = set(), []
    for bq in FLASH_BLOCK_CHOICES:
        for bk in FLASH_BLOCK_CHOICES:
            try:
                eff = (_pick_block(bq, seq_q), _pick_block(bk, seq_k))
            except ValueError:
                continue
            if eff in seen:
                continue
            seen.add(eff)
            out.append({"block_q": eff[0], "block_k": eff[1]})
    return out


def fused_step(donate_allowed: bool = True) -> List[dict]:
    """Remat (gradient checkpointing) on/off crossed with buffer
    donation on/off.  Donation candidates are only offered when the
    caller may legally donate (the compile cache forbids it: persisted
    executables must not rely on input-output aliasing)."""
    out = []
    for remat in (0, 1):
        for donate in ((1, 0) if donate_allowed else (0,)):
            out.append({"remat": remat, "donate": donate})
    return out


def _pow2_up_to(n: int) -> List[int]:
    out, b = [], 1
    while b < n:
        out.append(b)
        b *= 2
    out.append(n)
    return out


def lane_bucket_sets(max_lanes: int) -> List[Sequence[int]]:
    """Candidate decode lane-count bucket sets: pow2 ladder, single
    max-size bucket, min+max, and (small fleets) the dense ladder."""
    cands = [tuple(_pow2_up_to(max_lanes)), (max_lanes,)]
    if max_lanes > 1:
        cands.append((1, max_lanes))
    if 2 < max_lanes <= 16:
        cands.append(tuple(range(1, max_lanes + 1)))
    seen, out = set(), []
    for c in cands:
        if c in seen:
            continue
        seen.add(c)
        out.append(c)
    return out


def decode_engine(max_lanes: int, max_seq_len: int) -> List[dict]:
    """Lane-bucket sets x gen page sizes for the DecodeEngine."""
    out = []
    for buckets in lane_bucket_sets(max_lanes):
        for page in GEN_PAGE_SIZE_CHOICES:
            if page > max_seq_len:
                continue
            out.append({"lane_buckets": list(buckets),
                        "page_size": page})
    return out


DRAFT_K_CHOICES = (1, 2, 3, 4, 6, 8)


def draft_k() -> List[dict]:
    """Candidate speculative draft lengths (tokens proposed per
    iteration).  The engine scores them analytically — expected cost
    per accepted token under the configured acceptance hint — so the
    grid stays small and the tune is instant."""
    return [{"k": k} for k in DRAFT_K_CHOICES]


def serving_buckets(max_batch: int) -> List[dict]:
    """Candidate serving micro-batch bucket sets: pow2 ladder, single
    max bucket, halves ladder, and (small max) the dense ladder."""
    cands = [tuple(_pow2_up_to(max_batch)), (max_batch,)]
    halves, b = [], max_batch
    while b >= 1:
        halves.append(b)
        b //= 2
    cands.append(tuple(sorted(set(halves))))
    if 2 < max_batch <= 32:
        cands.append(tuple(range(1, max_batch + 1)))
    seen, out = set(), []
    for c in cands:
        if c in seen:
            continue
        seen.add(c)
        out.append({"buckets": list(c)})
    return out

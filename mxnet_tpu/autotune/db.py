"""TuningDB — the persistent winner store.

Each entry is one tuned site: ``(site, key, device kind, topology) →
best config``, written in the shared :mod:`mxnet_tpu.artifact_store`
grammar (same atomic CRC-checked file format, env-envelope
invalidation, and admin surface as the compile cache — one store
implementation, two artifact families).  The payload is plain JSON
(config + provenance), so a DB is inspectable with ``strings`` and
portable across jax versions — the env envelope invalidates on the
topology axes that change the right answer, not on the pickle ABI.

Lookup order: in-process memo, the primary DB dir
(``MXNET_AUTOTUNE_DIR``), then read-only overlays (attached AOT
bundles).  Every failure mode — missing file, CRC mismatch, torn
header, injected ``autotune.load`` fault — degrades to a miss (the
caller falls back to the built-in default config), never a crash.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional

from ..artifact_store import EntryStore, digest_of
from ..base import MXNetError, env

_MAGIC = b"MXTPUAT1"
_SCHEMA = 1
ENTRY_SUFFIX = ".mxt"

_STORE = EntryStore(_MAGIC, ENTRY_SUFFIX, "autotune", "autotune")


def _strict() -> bool:
    return bool(env("MXNET_AUTOTUNE_STRICT", 0, int))


def topology_fingerprint() -> dict:
    """The key half of the envelope: the axes along which a different
    machine needs a different winner (device kind, counts, backend) —
    a subset of :func:`compile_cache.env_fingerprint`, which is ALSO
    recorded whole in every entry as the invalidation envelope."""
    from ..compile_cache import env_fingerprint

    fp = env_fingerprint()
    return {k: fp[k] for k in ("platform", "device_kind", "device_count",
                               "process_count")}


class TuningDB:
    """Winner store over one writable dir plus read-only overlays."""

    def __init__(self, d: str = "", overlays: Optional[List[str]] = None):
        self._dir = d or ""
        self._overlays: List[str] = list(overlays or [])
        self._mem = {}  # digest -> {"config", "meta"}
        # bumped on every put/overlay change; cache_fingerprint() memoizes
        # against it so the compile-cache key only re-hashes on change
        self.generation = 0

    # -- keying -----------------------------------------------------------
    @staticmethod
    def digest(site: str, key: dict) -> str:
        parts = {"schema": _SCHEMA, "site": site, "key": key,
                 "topology": topology_fingerprint()}
        return digest_of(parts)

    def read_dirs(self) -> List[str]:
        out = [self._dir] if self._dir else []
        out.extend(self._overlays)
        return out

    def add_overlay(self, d: str) -> None:
        if d not in self._overlays:
            self._overlays.append(d)
            self.generation += 1

    # -- load / store -----------------------------------------------------
    def get(self, site: str, key: dict) -> Optional[dict]:
        """-> {"config", "meta"} or None (a miss — caller uses the
        built-in default).  Counts hits/misses; corruption degrades."""
        from . import _metrics

        digest = self.digest(site, key)
        ent = self._mem.get(digest)
        if ent is None:
            ent = self._load(digest)
        if ent is None:
            _metrics()["misses"].inc()
            return None
        _metrics()["hits"].inc()
        return ent

    def _load(self, digest: str) -> Optional[dict]:
        from . import _log_event, _metrics
        from .. import faults
        from ..compile_cache import env_fingerprint
        from ..filesystem import verify_crc_sidecar

        for d in self.read_dirs():
            path = _STORE.entry_path(d, digest)
            if not os.path.exists(path):
                continue
            try:
                faults.fire("autotune.load")
                if verify_crc_sidecar(path) is False:
                    raise MXNetError("CRC mismatch")
                meta, payload = _STORE.read_payload(path)
                if meta.get("env") != env_fingerprint():
                    _log_event("autotune_invalidate", path=path,
                               entry_env=meta.get("env"),
                               current_env=env_fingerprint())
                    continue  # stale-version entry: a miss, not an error
                body = json.loads(payload.decode())
                ent = {"config": body["config"], "meta": meta}
                self._mem[digest] = ent
                return ent
            except Exception as exc:
                _metrics()["errors"].inc()
                _log_event("autotune_corrupt", path=path,
                           error=repr(exc)[:300])
                if _strict():
                    raise
                continue
        return None

    def put(self, site: str, key: dict, config: dict,
            provenance: Optional[dict] = None) -> str:
        from . import _log_event, _metrics
        from ..compile_cache import env_fingerprint

        digest = self.digest(site, key)
        provenance = provenance or {}
        meta = {
            "digest": digest,
            "site": site,
            "key": key,
            "env": env_fingerprint(),
            "created": round(time.time(), 3),
            "objective": provenance.get("objective"),
            "score": provenance.get("score"),
            "measured_ms": provenance.get("measured_ms"),
            "tuning_ms": provenance.get("tuning_ms"),
        }
        self._mem[digest] = {"config": config, "meta": meta}
        self.generation += 1
        if self._dir:
            payload = json.dumps({"config": config,
                                  "provenance": provenance},
                                 sort_keys=True, default=str).encode()
            try:
                path = _STORE.write_entry(self._dir, digest, meta, payload)
                _metrics()["stores"].inc()
                _log_event("autotune_store", digest=digest, site=site,
                           path=path, config=config)
            except Exception as exc:
                _metrics()["errors"].inc()
                _log_event("autotune_store_failed", digest=digest,
                           site=site, error=repr(exc)[:300])
                if _strict():
                    raise
        return digest

    def all_digests(self) -> List[str]:
        """Every winner visible to this DB (memo + dirs + overlays) —
        the compile-cache key material: a different winner set is a
        different set of programs."""
        seen = set(self._mem)
        for d in self.read_dirs():
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(ENTRY_SUFFIX):
                    seen.add(name[:-len(ENTRY_SUFFIX)])
        return sorted(seen)

    def export_entries(self, dest: str) -> int:
        """Copy every visible winner into ``dest`` (AOT bundle carry).
        In-memory-only winners are materialized as fresh entries."""
        n = 0
        os.makedirs(dest, exist_ok=True)
        exported = set()
        for d in self.read_dirs():
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if not name.endswith(ENTRY_SUFFIX) or name in exported:
                    continue
                src = os.path.join(d, name)
                try:
                    meta, payload = _STORE.read_payload(src)
                    _STORE.write_entry(dest, name[:-len(ENTRY_SUFFIX)],
                                       meta, payload)
                    exported.add(name)
                    n += 1
                except Exception:
                    continue
        for digest, ent in sorted(self._mem.items()):
            if digest + ENTRY_SUFFIX in exported:
                continue
            body = {"config": ent["config"], "provenance": {}}
            try:
                _STORE.write_entry(dest, digest, ent["meta"],
                                   json.dumps(body, sort_keys=True,
                                              default=str).encode())
                n += 1
            except Exception:
                continue
        return n


# -- admin surface (tools/autotune_admin.py) -------------------------------

def _env_compatible(meta: dict) -> bool:
    from ..compile_cache import env_fingerprint

    return meta.get("env") == env_fingerprint()


def ls_entries(d: str) -> List[dict]:
    """[{digest, path, bytes, mtime, site, objective, score, env_ok}]."""
    return _STORE.ls_entries(
        d, meta_fields=lambda meta: {"site": meta.get("site"),
                                     "objective": meta.get("objective"),
                                     "score": meta.get("score"),
                                     "env_ok": _env_compatible(meta)})


def verify_entry(path: str):
    """(ok, detail): CRC sidecar + header + payload-JSON check."""
    def _check(meta, payload):
        body = json.loads(payload.decode())
        if "config" not in body:
            raise MXNetError("entry has no config")

    return _STORE.verify_entry(path, payload_check=_check,
                               env_ok=_env_compatible)


def prune(d: str, budget_mb: int) -> List[str]:
    from . import _log_event

    removed = _STORE.prune(d, budget_mb)
    if removed:
        _log_event("autotune_pruned", dir=d, removed=len(removed))
    return removed


def show_winner(path: str) -> dict:
    """Full entry (meta + config + provenance) for one entry file."""
    meta, payload = _STORE.read_payload(path)
    body = json.loads(payload.decode())
    return {"meta": meta, "config": body.get("config"),
            "provenance": body.get("provenance")}

"""The tuning loop: enumerate a declared space, score every candidate,
record the winner with provenance.

Objective ladder (cheapest that applies wins):

* ``score_fn`` — closed-form analytic cost (bucket sets: expected
  padding waste + per-executable compile cost).  No compiler involved.
* ``build_fn`` — per-candidate lower + XLA cost analysis via the shared
  :func:`mxnet_tpu.hlo_analysis.lower_and_analyze`, scored by the
  roofline bound max(flops/peak, bytes/bandwidth).  Runs on CPU with no
  chip: lowering is shape-only, and the RANKING across candidates of
  the same program tracks the roofline even when absolute times don't.
* ``measure_fn`` — real timed execution of the top-K proxy candidates,
  used when a device is present (or ``MXNET_AUTOTUNE_MEASURE=1``
  forces it).  The measured winner overrides the proxy ranking.

Ties break on the candidate's canonical JSON, so the winner is a pure
function of (space, objective) — deterministic across processes.
"""
from __future__ import annotations

import json
import time
from typing import Callable, List, Optional

from ..base import env

__all__ = ["Tuner"]


def _cand_key(cand: dict) -> str:
    return json.dumps(cand, sort_keys=True, default=str)


class Tuner:
    def __init__(self, db, topk: Optional[int] = None,
                 measure: Optional[bool] = None):
        self._db = db
        self._topk = int(env("MXNET_AUTOTUNE_TOPK", 3, int)
                         if topk is None else topk)
        if measure is None:
            measure = bool(env("MXNET_AUTOTUNE_MEASURE", 0, int))
            if not measure:
                try:
                    import jax

                    measure = jax.default_backend() == "tpu"
                except Exception:
                    measure = False
        self._measure = bool(measure)

    def tune(self, site: str, key: dict, candidates: List[dict],
             build_fn: Optional[Callable] = None,
             score_fn: Optional[Callable] = None,
             measure_fn: Optional[Callable] = None,
             default: Optional[dict] = None) -> Optional[dict]:
        """Score ``candidates`` and persist the winner.  Returns the
        winning config, or ``default`` when nothing scores (every
        candidate failed to build) — in which case nothing is stored and
        the site keeps consulting its built-in default."""
        from . import _log_event, _metrics

        t0 = time.perf_counter()
        scored = []  # (score, cand_key, cand)
        objective = "analytic" if score_fn is not None else "roofline_proxy"
        for cand in candidates:
            try:
                if score_fn is not None:
                    score = float(score_fn(cand))
                elif build_fn is not None:
                    from ..hlo_analysis import lower_and_analyze, roofline_ms

                    fn, abstract = build_fn(cand)
                    _, info = lower_and_analyze(fn, abstract)
                    score = roofline_ms(info)
                    if score is None:
                        raise ValueError("no cost analysis")
                else:
                    raise ValueError("tune() needs score_fn or build_fn")
            except Exception as exc:
                _log_event("autotune_candidate_failed", site=site,
                           config=cand, error=repr(exc)[:200])
                continue
            scored.append((score, _cand_key(cand), cand))
        if not scored:
            _log_event("autotune_no_winner", site=site,
                       candidates=len(candidates))
            return default
        scored.sort(key=lambda t: (t[0], t[1]))
        winner = scored[0][2]
        provenance = {
            "objective": objective,
            "score": scored[0][0],
            "scores": [[c, s] for s, _, c in scored],
            "candidates": len(candidates),
        }
        if measure_fn is not None and self._measure:
            measured = []
            for score, ck, cand in scored[:max(1, self._topk)]:
                try:
                    ms = float(measure_fn(cand))
                except Exception as exc:
                    _log_event("autotune_measure_failed", site=site,
                               config=cand, error=repr(exc)[:200])
                    continue
                measured.append((ms, ck, cand))
            if measured:
                measured.sort(key=lambda t: (t[0], t[1]))
                winner = measured[0][2]
                provenance["objective"] = "measured"
                provenance["measured_ms"] = {ck: round(ms, 4)
                                             for ms, ck, _ in measured}
                provenance["score"] = measured[0][0]
        tuning_ms = (time.perf_counter() - t0) * 1e3
        provenance["tuning_ms"] = round(tuning_ms, 1)
        _metrics()["tuning_ms"].observe(tuning_ms)
        self._db.put(site, key, winner, provenance)
        _log_event("autotune_winner", site=site, config=winner,
                   objective=provenance["objective"],
                   score=provenance["score"],
                   tuning_ms=provenance["tuning_ms"])
        return winner

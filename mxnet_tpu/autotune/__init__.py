"""Persistent autotuner — search kernel/compiler knobs once per
(model, topology), pay the tuning cost once per fleet.

ROADMAP item 1 promoted the manual perf loop (a human sweeping
``tools/flash_ab.py`` block configs by hand) into a framework
subsystem, following the TVM autotuning loop (arXiv 1802.04799) with
XLA cost analysis as the cheap proxy objective in the spirit of a
learned TPU cost model (arXiv 2008.01040):

* each tunable site (flash-attention blocks, fused-step remat/donation,
  decode-engine lane buckets and page size, serving micro-batch
  buckets) declares its search space in :mod:`.spaces`;
* the :class:`.Tuner` scores candidates per-candidate via
  lower + XLA cost analysis (roofline proxy, runnable on CPU with no
  chip), optionally refining the top-K by real timed execution when a
  device is present;
* winners persist in the :class:`.TuningDB` — the same atomic
  CRC-checked entry format, env-envelope invalidation, and admin
  surface as the compile cache (shared :mod:`..artifact_store`
  helpers) — so a whole fleet inherits one host's tuning;
* the chosen config joins the compile-cache key (tuned and untuned
  executables never collide) and AOT bundles carry the tuning entries,
  so a restored replica is tuned-by-construction.

Modes (``MXNET_AUTOTUNE``): empty/``off`` — sites use their built-in
defaults, zero overhead; ``1``/``on`` — sites consult the DB (lookup
only; a miss is the default config); ``record`` — a DB miss runs the
tuning loop and persists the winner.
"""
from __future__ import annotations

import threading
from typing import Optional

from ..base import env, register_env

from .db import TuningDB  # noqa: F401  (re-export)
from .tuner import Tuner  # noqa: F401  (re-export)
from . import spaces  # noqa: F401  (re-export)

__all__ = ["TuningDB", "Tuner", "spaces", "mode", "enabled", "db",
           "db_dir", "get_or_tune", "lookup", "stats", "reset_for_tests",
           "cache_fingerprint", "export_to_bundle",
           "attach_bundle_overlay"]

register_env("MXNET_AUTOTUNE", "", str,
             "Autotuner mode: empty/off = sites use built-in defaults; "
             "1/on = consult the tuning DB at lowering time (lookup "
             "only); record = tune on a DB miss and persist the winner.")
register_env("MXNET_AUTOTUNE_DIR", "", str,
             "Directory for the persistent tuning DB. Empty derives "
             "<MXNET_COMPILE_CACHE_DIR>/autotune when the compile cache "
             "is enabled, else the DB is in-memory only.")
register_env("MXNET_AUTOTUNE_TOPK", 3, int,
             "How many proxy-ranked candidates the Tuner re-scores by "
             "real timed execution when measurement is available.")
register_env("MXNET_AUTOTUNE_MEASURE", 0, int,
             "1 forces timed top-K refinement even off-TPU (on-TPU it "
             "is automatic); 0 trusts the roofline proxy off-chip.")
register_env("MXNET_AUTOTUNE_STRICT", 0, int,
             "1 makes tuning-DB load/store failures raise instead of "
             "degrading to the built-in default config (debugging aid).")

_lock = threading.Lock()
_db_cache: Optional[TuningDB] = None
_fp_cache = None  # (generation, mode) -> digest memo for cache_fingerprint
_instruments = None


def mode() -> str:
    """'off' | 'on' | 'record'."""
    v = env("MXNET_AUTOTUNE", "", str).strip().lower()
    if v in ("", "0", "off"):
        return "off"
    if v == "record":
        return "record"
    return "on"


def enabled() -> bool:
    return mode() != "off"


def db_dir() -> str:
    d = env("MXNET_AUTOTUNE_DIR", "", str)
    if d:
        return d
    cc = env("MXNET_COMPILE_CACHE_DIR", "", str)
    if cc:
        import os

        return os.path.join(cc, "autotune")
    return ""


def db() -> TuningDB:
    """Process-wide DB singleton (rebuilt when the dir env changes)."""
    global _db_cache
    with _lock:
        d = db_dir()
        if _db_cache is None or _db_cache._dir != d:
            overlays = _db_cache._overlays if _db_cache is not None else []
            _db_cache = TuningDB(d, overlays=overlays)
        return _db_cache


# -- telemetry instruments --------------------------------------------------

def _metrics():
    global _instruments
    if _instruments is None:
        from .. import telemetry as tm

        reg = tm.registry()
        _instruments = {
            "hits": reg.counter(
                "mxtpu_autotune_hits_total",
                "Tunable-site lookups satisfied by a tuning-DB winner."),
            "misses": reg.counter(
                "mxtpu_autotune_misses_total",
                "Tunable-site lookups that fell back to the built-in "
                "default (no DB entry for this key)."),
            "stores": reg.counter(
                "mxtpu_autotune_stores_total",
                "Tuning winners written to the DB."),
            "errors": reg.counter(
                "mxtpu_autotune_errors_total",
                "Tuning-DB load/store failures degraded to the default "
                "config (corrupt entry, torn write, injected fault)."),
            "tuning_ms": reg.histogram(
                "mxtpu_autotune_tuning_ms",
                "Wall time per tuning-loop run (ms).",
                start=1.0, factor=4.0, count=12),
        }
    return _instruments


def _log_event(kind, **fields):
    try:
        from .. import telemetry as tm

        tm.log_event(kind, **fields)
    except Exception:
        pass


def stats() -> dict:
    """Compact counters for BENCH / capture records."""
    m = _metrics()
    return {
        "mode": mode(),
        "dir": db_dir() or None,
        "hits": m["hits"].value,
        "misses": m["misses"].value,
        "stores": m["stores"].value,
        "errors": m["errors"].value,
        "tuning_ms": round(m["tuning_ms"].sum, 1),
    }


def reset_for_tests() -> None:
    """Drop the DB singleton, fingerprint memo, and instrument handles."""
    global _db_cache, _fp_cache, _instruments
    with _lock:
        _db_cache = None
        _fp_cache = None
        _instruments = None


# -- the site-facing API ----------------------------------------------------

def lookup(site: str, key: dict) -> Optional[dict]:
    """Winner config for (site, key), or None.  Off mode: always None
    without touching the DB (zero overhead on the default path)."""
    if mode() == "off":
        return None
    ent = db().get(site, key)
    return ent["config"] if ent else None


def get_or_tune(site: str, key: dict, candidates=None, build_fn=None,
                score_fn=None, measure_fn=None,
                default: Optional[dict] = None) -> Optional[dict]:
    """The one call every tunable site makes at lowering time.

    off: ``default``.  on: DB winner or ``default``.  record: DB winner,
    else run the tuning loop over ``candidates``, persist, and return
    the fresh winner (``default`` when every candidate fails)."""
    m = mode()
    if m == "off":
        return default
    ent = db().get(site, key)
    if ent is not None:
        return ent["config"]
    if m != "record" or not candidates:
        return default
    return Tuner(db()).tune(site, key, candidates, build_fn=build_fn,
                            score_fn=score_fn, measure_fn=measure_fn,
                            default=default)


def cache_fingerprint() -> Optional[str]:
    """Compile-cache key material: None when off (key unchanged — old
    entries stay valid), else a digest over the full visible winner
    set.  Conservative by design: ANY winner change
    re-keys every executable, so tuned and untuned programs can never
    collide under one digest."""
    global _fp_cache
    if mode() == "off":
        return None
    d = db()
    tag = (d.generation, d._dir)
    with _lock:
        if _fp_cache is not None and _fp_cache[0] == tag:
            return _fp_cache[1]
    from ..artifact_store import digest_of

    # deliberately NOT keyed on record-vs-on: both modes see the same
    # winner set, so executables compiled while recording deserialize
    # unchanged on the lookup-mode fleet
    fp = digest_of({"entries": d.all_digests()})
    with _lock:
        _fp_cache = (tag, fp)
    return fp


# -- AOT bundle integration (compile_cache.save_bundle/attach_bundle) ------

def export_to_bundle(bundle_path: str) -> int:
    """Copy every visible tuning entry into ``<bundle>/autotune`` so the
    bundle restores a replica tuned-by-construction.  Returns the entry
    count (0 when there is nothing to carry)."""
    import os

    d = db()
    if not d.all_digests():
        return 0
    return d.export_entries(os.path.join(bundle_path, "autotune"))


def attach_bundle_overlay(bundle_path: str) -> bool:
    """Attach ``<bundle>/autotune`` as a read-only DB overlay (no-op
    when the bundle carries no tuning entries)."""
    import os

    sub = os.path.join(bundle_path, "autotune")
    if not os.path.isdir(sub):
        return False
    db().add_overlay(sub)
    global _fp_cache
    with _lock:
        _fp_cache = None
    _log_event("autotune_bundle_attached", path=sub)
    return True

"""``mx.rtc`` — runtime compilation of user kernel SOURCE STRINGS.

Parity: the reference compiles raw CUDA C strings with NVRTC at runtime and
launches them on NDArrays (/root/reference/src/common/mxrtc.cc:117-135,
python/mxnet/rtc.py).  The TPU-native equivalent compiles a PALLAS kernel
from source text at runtime: the string defines a function
``kernel(<in_ref...>, <out_ref...>)`` over Pallas Refs; it is compiled on
first call and dispatched on NDArrays with the same ``__call__`` shape as
the reference's MXRtc.

    krnl = mx.rtc.MXRtc("axpy", [("x", x), ("y", y)], [("out", out)], '''
    def kernel(x_ref, y_ref, out_ref):
        out_ref[...] = 2.0 * x_ref[...] + y_ref[...]
    ''')
    krnl.push([x, y], [out])

For registering kernels as named graph ops (trainable, custom vjp) use
``mx.register_pallas_op`` — MXRtc is the imperative escape hatch.
"""
from __future__ import annotations

import textwrap
from typing import List, Sequence, Tuple

from .base import MXNetError

__all__ = ["MXRtc"]


class MXRtc:
    """Compile ``kernel_src`` (Python/Pallas source) at runtime and run it
    imperatively on NDArrays.

    Parameters mirror the reference MXRtc: ``name``, ``inputs`` and
    ``outputs`` as (name, NDArray) prototype pairs fixing rank/dtype, and
    the kernel source string.  The reference's grid/block launch dims are
    derived automatically here (whole-array blocks); pass ``grid`` and
    Pallas ``in_specs``/``out_specs`` through ``**pallas_kwargs`` for tiled
    launches.
    """

    def __init__(self, name: str, inputs: Sequence[Tuple[str, object]],
                 outputs: Sequence[Tuple[str, object]], kernel_src: str,
                 **pallas_kwargs):
        self.name = name
        self._in_protos = [(n, tuple(a.shape)) for n, a in inputs]
        self._out_protos = [(n, tuple(a.shape), a.dtype)
                            for n, a in outputs]
        self._pallas_kwargs = dict(pallas_kwargs)
        src = textwrap.dedent(kernel_src)
        srcfile = "<mx.rtc:%s>" % name
        scope = {}
        try:
            exec(compile(src, srcfile, "exec"), scope)
        except Exception as e:
            raise MXNetError("rtc kernel %r failed to compile: %s"
                             % (name, e))
        fn = scope.get("kernel")
        if fn is None:
            # accept a single function DEFINED in the source under any name
            # (imported callables don't count — reference kernels are named
            # by the user)
            fns = [v for v in scope.values()
                   if callable(v) and
                   getattr(getattr(v, "__code__", None), "co_filename",
                           None) == srcfile]
            if len(fns) != 1:
                raise MXNetError(
                    "rtc kernel source must define exactly one function "
                    "(preferably named 'kernel')")
            fn = fns[0]
        self._kernel = fn
        self._compiled = None

    def _build(self):
        import jax
        from jax.experimental import pallas as pl

        out_shape = [jax.ShapeDtypeStruct(shape, dtype)
                     for _, shape, dtype in self._out_protos]
        call = pl.pallas_call(
            self._kernel,
            out_shape=out_shape if len(out_shape) > 1 else out_shape[0],
            interpret=jax.default_backend() != "tpu",
            **self._pallas_kwargs)
        self._compiled = jax.jit(lambda *a: call(*a))

    def push(self, ins, outs, grid_dims=None, block_dims=None):
        """Run the kernel (reference MXRtc.push signature; the launch dims
        are accepted for API parity — Pallas derives its own grid unless
        one was supplied at construction)."""
        from . import ndarray as nd

        if self._compiled is None:
            self._build()
        if len(ins) != len(self._in_protos):
            raise MXNetError(
                "rtc %r expects %d inputs, got %d"
                % (self.name, len(self._in_protos), len(ins)))
        for arr, (pname, shape) in zip(ins, self._in_protos):
            if tuple(arr.shape) != shape:
                raise MXNetError(
                    "rtc %r input %s shape %s does not match prototype %s"
                    % (self.name, pname, tuple(arr.shape), shape))
        if len(outs) != len(self._out_protos):
            raise MXNetError(
                "rtc %r expects %d outputs, got %d"
                % (self.name, len(self._out_protos), len(outs)))
        for out, (pname, shape, dtype) in zip(outs, self._out_protos):
            if tuple(out.shape) != shape:
                raise MXNetError(
                    "rtc %r output %s shape %s does not match prototype %s"
                    % (self.name, pname, tuple(out.shape), shape))
        vals = [a._data if isinstance(a, nd.NDArray) else a for a in ins]
        result = self._compiled(*vals)
        if not isinstance(result, (list, tuple)):
            result = [result]
        for out, res in zip(outs, result):
            out._set(res)
        return outs

    __call__ = push

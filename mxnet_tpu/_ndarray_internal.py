"""Internal-op namespace (reference python/mxnet/_ndarray_internal.py:
the codegen target module holding the ``_``-prefixed imperative ops).
Here every registered op — public and internal — is generated straight
into ``mxnet_tpu.ndarray``; this module re-exports the underscore subset
under the reference's import path for code that does
``from mxnet._ndarray_internal import _plus_scalar``-style imports."""
from . import ndarray as _nd


def __getattr__(name):
    if name.startswith("_") and not name.startswith("__") \
            and hasattr(_nd, name):
        return getattr(_nd, name)
    raise AttributeError("no internal NDArray op %r" % name)


def __dir__():
    return [n for n in dir(_nd) if n.startswith("_") and
            not n.startswith("__")]

"""NDArray — the imperative n-dim array over ``jax.Array``.

TPU-native redesign of /root/reference/include/mxnet/ndarray.h:33-374 +
src/ndarray/ndarray.cc.  The reference NDArray is a ref-counted chunk whose
every mutation is pushed to the dependency engine; here the "engine" is JAX's
async dispatch — every op returns immediately with a future-backed
``jax.Array``; ``wait_to_read`` ≈ ``block_until_ready`` (ndarray.h:153-168).
Mutation keeps MXNet surface semantics (``a[:] = x``, ``a += b``, ``out=``)
by rebinding the underlying immutable buffer on the same Python object, so
holders of the NDArray (executors, optimizers) observe updates.

The whole ``mx.nd.<op>`` function surface is generated from the op registry
at import, mirroring the reference's import-time codegen from the C op
registry (python/mxnet/_ctypes/ndarray.py:165-200).

Save/load keeps the reference's binary ``.params`` format bit-for-bit
(src/ndarray/ndarray.cc:633-714: magic 0x112, TShape uint32s, Context two
int32s, mshadow type flag, raw buffer; dmlc vector<string> keys).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Union

import numpy as np

from .base import MXNetError, mx_real_t
from .context import Context, current_context
from .ops import OpContext, registered_ops
from .ops.param import _np_dtype
from . import random as _random

_pyslice = slice  # op autogen shadows builtins (slice/sum/max/...) at module level
_pyabs = abs

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "load", "save", "imdecode", "onehot_encode",
           "waitall", "moveaxis"]


def _default_ctx(ctx) -> Context:
    return ctx if ctx is not None else current_context()


def _as_jax(x, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    if isinstance(x, NDArray):
        data = x._data
    elif isinstance(x, np.ndarray):
        data = jnp.asarray(x)
    elif isinstance(x, (int, float, np.generic)):
        data = jnp.asarray(x, dtype or mx_real_t)
    else:
        # Python lists/tuples default to float32 like the reference's
        # nd.array (python/mxnet/ndarray.py array(): dtype=float32 unless
        # the source carries its own dtype).
        data = jnp.asarray(x, dtype or mx_real_t)
    if dtype is not None:
        dt = _np_dtype(dtype) if isinstance(dtype, str) else dtype
        if data.dtype != dt:
            data = data.astype(dt)
    return data


# Hook installed by comm_engine: called with the NDArray before any host
# read so an in-flight async kvstore pull targeting it completes first
# (the reference engine's WaitToRead dependency, threaded_engine.h).
_async_read_guard = None


class NDArray:
    """n-dim array on a device context (reference: include/mxnet/ndarray.h)."""

    __slots__ = ("_data", "_ctx", "writable")

    def __init__(self, data, ctx: Optional[Context] = None, writable: bool = True):
        import jax.numpy as jnp

        if isinstance(data, NDArray):
            data = data._data
        elif isinstance(data, np.ndarray) or np.isscalar(data):
            data = jnp.asarray(data)
        self._data = data
        self._ctx = _default_ctx(ctx)
        self.writable = writable

    # -- properties --------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        return self._ctx

    @property
    def ctx(self) -> Context:
        return self._ctx

    @property
    def handle(self):
        return self  # parity shim: C-handle == the object itself

    # -- sync / host transfer ---------------------------------------------
    def wait_to_read(self):
        """Block until the async value is materialised (ndarray.h:153-160).
        When an async kvstore pull targets this array, also block until that
        pull lands (the engine's WaitToRead contract, comm_engine.py)."""
        g = _async_read_guard
        if g is not None:
            g(self)
        self._data.block_until_ready()

    def wait_to_write(self):
        g = _async_read_guard
        if g is not None:
            g(self)
        self._data.block_until_ready()

    def asnumpy(self) -> np.ndarray:
        g = _async_read_guard
        if g is not None:
            g(self)
        x = self._data
        # multi-process (global-mesh) arrays: a fully-replicated array has a
        # complete local copy on every process — read that; a sharded global
        # array has no local materialization and the caller should use the
        # executor-group accessors that return the process-local slice
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            if getattr(x, "is_fully_replicated", False):
                return np.asarray(x.addressable_shards[0].data)
            raise MXNetError(
                "array is sharded across processes; use the module/executor "
                "accessors (get_outputs) for the process-local slice")
        return np.asarray(x)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def astype(self, dtype) -> "NDArray":
        if isinstance(dtype, str):
            dtype = _np_dtype(dtype)
        return NDArray(self._data.astype(dtype), self._ctx)

    # -- copies / context moves -------------------------------------------
    def copy(self) -> "NDArray":
        return NDArray(self._data, self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        """Copy into a destination array or context (reference CopyFromTo,
        ndarray.cc:250-328 — device-pair dispatch is jax.device_put here)."""
        import jax

        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError("shape mismatch in copyto")
            other._set(jax.device_put(self._data, other._ctx.jax_device())
                       .astype(other.dtype))
            return other
        ctx = Context(other)
        return NDArray(jax.device_put(self._data, ctx.jax_device()), ctx)

    def as_in_context(self, context: Context) -> "NDArray":
        if context == self._ctx:
            return self
        return self.copyto(context)

    def _set(self, data):
        if not self.writable:
            raise MXNetError("trying to write to a readonly NDArray")
        self._data = data

    # -- shape ops (zero-copy in XLA; reference ndarray.h:286-352) ---------
    def reshape(self, shape) -> "NDArray":
        if isinstance(shape, int):
            shape = (shape,)
        from .ops.matrix import _reshape_target

        return NDArray(self._data.reshape(_reshape_target(self.shape, shape)), self._ctx)

    def broadcast_to(self, shape) -> "NDArray":
        """Broadcast along extent-1 axes to ``shape`` (reference
        ndarray.py broadcast_to). A shorter current shape is left-padded
        with 1s like the reference; 0 in the target keeps the input
        extent (the registered op's convention — this method delegates
        to it so the two surfaces cannot diverge)."""
        shape = tuple(int(d) for d in shape)
        cur = self
        if len(self.shape) < len(shape):
            cur = self.reshape(
                (1,) * (len(shape) - len(self.shape)) + self.shape)
        if len(cur.shape) != len(shape):
            raise ValueError("cannot broadcast %s to lower-rank %s"
                             % (self.shape, shape))
        if any(c != t and c != 1 and t != 0
               for c, t in zip(cur.shape, shape)):
            raise ValueError(
                "cannot broadcast %s to %s (only extent-1 axes "
                "broadcast)" % (self.shape, shape))
        return _invoke("broadcast_to", (cur,), {"shape": shape})

    @property
    def T(self) -> "NDArray":
        return NDArray(self._data.T, self._ctx)

    def slice(self, start, stop) -> "NDArray":
        """Return a sub-array over axis 0.

        DOCUMENTED DEVIATION from the reference: ``Slice``/``__getitem__``
        there return zero-copy aliases of the parent's storage
        (include/mxnet/ndarray.h:286-352) so writes through a slice mutate
        the parent.  ``jax.Array`` is immutable, so slices here are
        independent copies; write into a region with ``a[i:j] = v`` on the
        parent instead.  Covered by tests/unittest/test_ndarray.py.
        """
        return NDArray(self._data[start:stop], self._ctx)

    def __len__(self):
        return self.shape[0]

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key._data
        return NDArray(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value, self.dtype)
        if isinstance(key, NDArray):
            key = key._data
        if isinstance(key, _pyslice) and key == _pyslice(None):
            if np.isscalar(value):
                self._set(jnp.full(self.shape, value, self.dtype))
            else:
                value = jnp.asarray(value, self.dtype)
                self._set(jnp.broadcast_to(value, self.shape))
        else:
            self._set(self._data.at[key].set(value))

    # -- arithmetic --------------------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return _invoke(op, (a, b), {})
        if np.isscalar(other):
            return _invoke(scalar_op, (self,), {"scalar": float(other)})
        raise TypeError("unsupported operand type %s" % type(other))

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_rdiv_scalar", reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_rmod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_rpower_scalar", reverse=True)

    def __neg__(self):
        return _invoke("negative", (self,), {})

    def __abs__(self):
        return _invoke("abs", (self,), {})

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __iadd__(self, o):
        out = self.__add__(o)
        self._set(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._set(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._set(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._set(out._data)
        return self

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements "
                         "is ambiguous")

    def __repr__(self):
        return "<NDArray %s @%s>\n%s" % (
            "x".join(str(s) for s in self.shape), self._ctx, self.asnumpy())

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx_type": self._ctx.device_type,
                "ctx_id": self._ctx.device_id, "writable": self.writable}

    def __setstate__(self, state):
        import jax.numpy as jnp

        self._data = jnp.asarray(state["data"])
        self._ctx = Context(state["ctx_type"], state["ctx_id"])
        self.writable = state["writable"]


# ---------------------------------------------------------------------------
# Imperative invoke — the analogue of MXImperativeInvoke
# (/root/reference/src/c_api/c_api_ndarray.cc:323)
# ---------------------------------------------------------------------------


def _is_tensor_arg(v) -> bool:
    """True for tensor-like kwargs (NDArray / ndarray / jax.Array).  numpy
    scalars (``np.float32(2.0)``) carry dtype+shape but are attrs, not
    tensor inputs."""
    if isinstance(v, NDArray):
        return True
    if isinstance(v, np.generic):
        return False
    if isinstance(v, np.ndarray):
        return True
    return hasattr(v, "dtype") and hasattr(v, "shape") and hasattr(v, "ndim")


def _invoke(op_name: str, args, kwargs):
    op = registered_ops()[op_name]
    out = kwargs.pop("out", None)
    kwargs.pop("name", None)
    nd_kwargs = {}
    attrs = {}
    for k, v in kwargs.items():
        if _is_tensor_arg(v):
            nd_kwargs[k] = v
        else:
            attrs[k] = v
    pos_inputs = [a for a in args if a is not None]
    if op.key_var_num_args and op.key_var_num_args not in attrs:
        attrs[op.key_var_num_args] = len(pos_inputs)
    parsed = op.parse_attrs(attrs)
    names = op.input_names(parsed) + op.aux_names(parsed)
    inputs = list(pos_inputs)
    if nd_kwargs:
        slot = {n: a for n, a in zip(names, inputs)}
        slot.update(nd_kwargs)
        inputs = [slot[n] for n in names if n in slot]
    ctx = None
    for a in inputs:
        if isinstance(a, NDArray):
            ctx = a.context
            break
    if ctx is None:
        ctx_attr = parsed.get("ctx")
        if ctx_attr:
            dt, _, di = str(ctx_attr).partition("(")
            ctx = Context(dt, int(di.rstrip(")")) if di else 0)
        else:
            ctx = current_context()
    jarrs = [a._data if isinstance(a, NDArray) else _as_jax(a) for a in inputs]
    n_aux = len(op.aux_names(parsed))
    aux_in = tuple(jarrs[len(jarrs) - n_aux:]) if n_aux else ()
    main_in = jarrs[: len(jarrs) - n_aux] if n_aux else jarrs
    opctx = OpContext(is_train=False,
                      rng=_random.next_key() if op.stochastic else None)
    outs, aux_updates = op.apply(opctx, parsed, main_in, aux_in)
    # write aux updates back (engine-mutation parity for aux states)
    if n_aux:
        for holder, new in zip(inputs[len(inputs) - n_aux:], aux_updates):
            if isinstance(holder, NDArray):
                holder._set(new)
    results = [NDArray(o, ctx) for o in outs]
    from .base import env as _env

    if _env("MXNET_ENGINE_TYPE") == "NaiveEngine":
        # NaiveEngine debug contract: synchronous execution, block after
        # every op (reference src/engine/naive_engine.cc — executes on push)
        for r in results:
            r._data.block_until_ready()
    if out is not None:
        outs_t = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_t, results):
            dst._set(src._data.astype(dst.dtype) if dst.dtype != src.dtype else src._data)
        return out
    if len(results) == 1:
        return results[0]
    return results


def _make_imperative(op_name: str, op):
    def fn(*args, **kwargs):
        return _invoke(op_name, args, kwargs)

    fn.__name__ = op_name
    fn.__doc__ = op.doc or "Auto-generated imperative wrapper for op %s" % op_name
    return fn


def _init_ops():
    g = globals()
    for name, op in registered_ops().items():
        fn = _make_imperative(name, op)
        g[name] = fn
        if name.startswith("_") or name in __all__:
            continue
        __all__.append(name)


# ---------------------------------------------------------------------------
# Creation functions
# ---------------------------------------------------------------------------


def array(source_array, ctx=None, dtype=None) -> NDArray:
    import jax
    import jax.numpy as jnp

    carries_dtype = isinstance(source_array, (NDArray, np.ndarray, np.generic))
    if isinstance(source_array, NDArray):
        arr = source_array._data
    else:
        arr = np.asarray(source_array)
    if dtype is None:
        if not carries_dtype:
            dtype = mx_real_t  # python lists default to float32 (reference array())
        elif arr.dtype == np.float64:
            dtype = mx_real_t  # reference defaults to float32
        elif arr.dtype == np.int64:
            dtype = np.int32
        else:
            dtype = arr.dtype
    if isinstance(dtype, str):
        dtype = _np_dtype(dtype)
    ctx = _default_ctx(ctx)
    data = jax.device_put(jnp.asarray(arr, dtype), ctx.jax_device())
    return NDArray(data, ctx)


def empty(shape, ctx=None, dtype=mx_real_t) -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=mx_real_t) -> NDArray:
    import jax
    import jax.numpy as jnp

    if isinstance(shape, int):
        shape = (shape,)
    if isinstance(dtype, str):
        dtype = _np_dtype(dtype)
    ctx = _default_ctx(ctx)
    return NDArray(jax.device_put(jnp.zeros(shape, dtype), ctx.jax_device()), ctx)


def ones(shape, ctx=None, dtype=mx_real_t) -> NDArray:
    import jax
    import jax.numpy as jnp

    if isinstance(shape, int):
        shape = (shape,)
    if isinstance(dtype, str):
        dtype = _np_dtype(dtype)
    ctx = _default_ctx(ctx)
    return NDArray(jax.device_put(jnp.ones(shape, dtype), ctx.jax_device()), ctx)


def full(shape, val, ctx=None, dtype=mx_real_t) -> NDArray:
    import jax.numpy as jnp

    if isinstance(shape, int):
        shape = (shape,)
    if isinstance(dtype, str):
        dtype = _np_dtype(dtype)
    return NDArray(jnp.full(shape, val, dtype), _default_ctx(ctx))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=mx_real_t) -> NDArray:
    import jax.numpy as jnp

    if isinstance(dtype, str):
        dtype = _np_dtype(dtype)
    vals = np.arange(start, stop, step) if stop is not None else np.arange(start)
    if repeat > 1:
        vals = np.repeat(vals, repeat)
    return NDArray(jnp.asarray(vals, dtype), _default_ctx(ctx))


def moveaxis(tensor, source, destination) -> NDArray:
    import jax.numpy as jnp

    return NDArray(jnp.moveaxis(tensor._data, source, destination), tensor.context)


def concatenate(arrays, axis=0, always_copy=True) -> NDArray:
    import jax.numpy as jnp

    assert arrays, "arrays must not be empty"
    return NDArray(jnp.concatenate([a._data for a in arrays], axis=axis),
                   arrays[0].context)


def onehot_encode(indices, out) -> NDArray:
    return _invoke("_onehot_encode", (indices, out), {"out": out})


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3, mean=None):
    """Decode a JPEG/PNG buffer via the registered ``_imdecode`` op
    (reference python/mxnet/ndarray.py imdecode -> _imdecode NDArray
    function, ndarray.cc:796+): CHW float32 output, optional crop box and
    CHW mean subtraction — the reference's layout contract."""
    if isinstance(str_img, NDArray):
        buf = str_img
    else:
        data = str_img if isinstance(str_img, (bytes, bytearray)) \
            else bytes(str_img)
        buf = array(np.frombuffer(data, dtype=np.uint8))
    mean_arr = mean if mean is not None else array(
        np.zeros((0,), np.float32))
    x0, y0, x1, y1 = clip_rect if clip_rect else (0, 0, 0, 0)
    res = _invoke("_imdecode", (mean_arr, buf),
                  {"index": index, "x0": x0, "y0": y0, "x1": x1, "y1": y1,
                   "c": channels, "size": 0})
    if out is not None:
        # reference Imdecode writes into slice ``index`` of a 4-D batch
        # buffer (ndarray.cc: ret->Slice(index, index+1)); a 3-D out is
        # filled whole
        if out.ndim == 4:
            out[index:index + 1] = res.reshape((1,) + res.shape)
        else:
            out[:] = res
        return out
    return res


def waitall():
    """Block until all async work completes (reference: Engine WaitForAll via
    MXNDArrayWaitAll).  Blocks on every live ``jax.Array`` — the actual set of
    outstanding async results — plus the effects token stream."""
    import jax

    for a in jax.live_arrays():
        a.block_until_ready()
    jax.effects_barrier()


# ---------------------------------------------------------------------------
# Save / load — reference .params binary format, bit-for-bit
# (src/ndarray/ndarray.cc:633-714)
# ---------------------------------------------------------------------------

_MAGIC = 0x112
# mshadow type flags (mshadow/base.h enum order).  bfloat16 has NO flag in the
# reference enum: bf16 arrays are widened to float32 and saved as flag 0 so the
# file stays readable by the reference implementation (documented deviation —
# dtype is not round-tripped for bf16).
_TYPE_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3, "int32": 4,
              "int8": 5, "int64": 6}
_FLAG_TYPE = {v: k for k, v in _TYPE_FLAG.items()}


def _save_one(f, arr: NDArray):
    shape = arr.shape
    f.write(struct.pack("<I", len(shape)))
    if shape:
        f.write(struct.pack("<%dI" % len(shape), *shape))
    if len(shape) == 0:
        return
    dev_type = arr.context.device_typeid
    f.write(struct.pack("<ii", dev_type, arr.context.device_id))
    host = arr.asnumpy()
    dtype_name = str(np.dtype(host.dtype)) if host.dtype.kind != "V" else "bfloat16"
    if dtype_name not in _TYPE_FLAG:
        # bf16 (and any other type outside the reference enum) is widened to
        # float32 and declared as flag 0 so the payload matches the header.
        host = host.astype(np.float32)
        dtype_name = "float32"
    f.write(struct.pack("<i", _TYPE_FLAG[dtype_name]))
    f.write(host.tobytes())


def _load_one(f) -> NDArray:
    (ndim,) = struct.unpack("<I", f.read(4))
    shape = struct.unpack("<%dI" % ndim, f.read(4 * ndim)) if ndim else ()
    if ndim == 0:
        return NDArray(np.zeros(()), cpu_ctx())
    dev_type, dev_id = struct.unpack("<ii", f.read(8))
    (type_flag,) = struct.unpack("<i", f.read(4))
    if type_flag == 7:
        # legacy compat: earlier versions of THIS framework wrote bf16 arrays
        # with invented flag 7 and a float32-widened payload; read them as
        # float32.  (Upstream MXNet >=1.6 uses 7 for kBool, which the 0.9
        # reference this targets never emits.)
        dtype_name = "float32"
    elif type_flag not in _FLAG_TYPE:
        # guessing an element size here would desynchronize the stream and
        # silently corrupt every subsequent array in the container
        raise MXNetError("unknown mshadow type flag %d in .params file"
                         % type_flag)
    else:
        dtype_name = _FLAG_TYPE[type_flag]
    np_dtype = np.dtype(dtype_name)
    count = int(np.prod(shape))
    buf = f.read(count * np_dtype.itemsize)
    host = np.frombuffer(buf, dtype=np_dtype).reshape(shape)
    # Preserve the stored dtype exactly (reference NDArray::Load keeps the
    # type flag; array()'s float64->float32 default coercion must not apply).
    # 64-bit dtypes need JAX x64 mode; without it warn instead of silently
    # downcasting (TPUs have no native f64 — set JAX_ENABLE_X64=1 on CPU).
    import jax

    if np_dtype.itemsize == 8 and not jax.config.jax_enable_x64:
        import warnings

        warnings.warn(
            "loading %s array as %s: JAX x64 mode is disabled "
            "(set JAX_ENABLE_X64=1 to preserve 64-bit dtypes)"
            % (dtype_name, "float32" if np_dtype.kind == "f" else "int32"))
        np_dtype = np.dtype(np.float32 if np_dtype.kind == "f" else np.int32)
        host = host.astype(np_dtype)
    return array(host, dtype=np_dtype)


def cpu_ctx():
    from .context import cpu

    return cpu()


def _save_stream(f, data) -> None:
    """Write a .params container to any binary file object (the writer
    half of :func:`_load_stream`)."""
    if isinstance(data, NDArray):
        data = [data]
    names: List[str] = []
    arrays: List[NDArray] = []
    if isinstance(data, dict):
        for k, v in data.items():
            names.append(k)
            arrays.append(v)
    else:
        arrays = list(data)
    f.write(struct.pack("<QQ", _MAGIC, 0))
    f.write(struct.pack("<Q", len(arrays)))
    for arr in arrays:
        _save_one(f, arr)
    f.write(struct.pack("<Q", len(names)))
    for n in names:
        nb = n.encode("utf-8")
        f.write(struct.pack("<Q", len(nb)))
        f.write(nb)


def save(fname: str, data, checksum: bool = False,
         op: str = "params.write") -> None:
    """Save NDArrays in the reference's .params container format.

    Local paths are written atomically (tmp + fsync + ``os.replace``,
    filesystem.atomic_write): a crash mid-save can no longer leave a torn
    file that shadows the previous good one.  ``checksum`` additionally
    writes a CRC32 sidecar (checkpoint saves use this so discovery can
    reject silently-corrupted files)."""
    from .filesystem import atomic_write, local_path

    lp = local_path(fname)
    if lp is not None:
        atomic_write(lp, lambda f: _save_stream(f, data),
                     checksum=checksum, op=op)
        return
    from .filesystem import open_uri

    with open_uri(fname, "wb") as f:
        _save_stream(f, data)


def _load_stream(f):
    """Read a .params container from any binary file object."""
    magic, _res = struct.unpack("<QQ", f.read(16))
    if magic != _MAGIC:
        raise MXNetError("Invalid NDArray file format (magic %#x)" % magic)
    (n,) = struct.unpack("<Q", f.read(8))
    arrays = [_load_one(f) for _ in range(n)]
    (nk,) = struct.unpack("<Q", f.read(8))
    names = []
    for _ in range(nk):
        (ln,) = struct.unpack("<Q", f.read(8))
        names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays


def load(fname: str):
    """Load a .params container; returns dict if names present, else list."""
    with open(fname, "rb") as f:
        return _load_stream(f)


_init_ops()

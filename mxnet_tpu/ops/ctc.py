"""WarpCTC loss op — plugin-op parity.

Capability parity with the reference's warp-ctc plugin
(/root/reference/plugin/warpctc/warpctc-inl.h): a loss-output layer whose
forward is softmax over the alphabet and whose backward is the CTC
gradient w.r.t. the pre-softmax activations, with the head gradient
ignored (it IS the loss). The reference links Baidu's warp-ctc CUDA/C++
library; here the CTC recursion is optax's pure-JAX dynamic program, so
it fuses into the jitted step like every other op.

Contract (warpctc-inl.h:66-135):
  * data: 2-D ``(input_length * batch, alphabet)``, time-major rows;
  * label: ``batch * label_length`` ints, blank = 0; zeros are stripped
    to recover each sample's true label sequence (:85-98);
  * output: ``softmax(data)``; gradient: CTC grad, out_grad ignored.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register
from .param import Param


def _ctc_losses(data, label, input_length, label_length):
    """Per-sample CTC losses. data: (T*N, P) time-major; label: (N, L)."""
    tn, p = data.shape
    n = tn // input_length
    logits = data.reshape(input_length, n, p).transpose(1, 0, 2)  # (N, T, P)
    # strip blanks (0) preserving order: stable argsort moves zeros to the
    # tail, matching the plugin's removeBlank compaction (:101-110)
    lab = label.reshape(n, label_length).astype(jnp.int32)
    order = jnp.argsort(lab == 0, axis=1, stable=True)
    lab = jnp.take_along_axis(lab, order, axis=1)
    label_pad = (lab == 0).astype(jnp.float32)
    logit_pad = jnp.zeros(logits.shape[:2], jnp.float32)
    import optax

    return optax.ctc_loss(logits.astype(jnp.float32), logit_pad, lab,
                          label_pad, blank_id=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _warpctc_impl(data, label, input_length, label_length):
    return jax.nn.softmax(data, axis=-1)


def _warpctc_fwd(data, label, input_length, label_length):
    return jax.nn.softmax(data, axis=-1), (data, label)


def _warpctc_bwd(input_length, label_length, res, ct):
    del ct  # loss op: head gradient ignored (warpctc-inl.h Backward)
    data, label = res
    grad = jax.grad(
        lambda d: jnp.sum(_ctc_losses(d, label, input_length,
                                      label_length)))(
        data.astype(jnp.float32))
    return grad.astype(data.dtype), jnp.zeros_like(label)


_warpctc_impl.defvjp(_warpctc_fwd, _warpctc_bwd)


def _warpctc_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    n = d[0] // int(attrs["input_length"])
    lshape = in_shapes[1] if in_shapes[1] is not None \
        else (n, int(attrs["label_length"]))
    return [tuple(d), tuple(lshape)], [tuple(d)], []


@register("WarpCTC", inputs=("data", "label"),
          params={"label_length": Param(int, required=True),
                  "input_length": Param(int, required=True)},
          infer_shape=_warpctc_infer, no_grad_inputs=("label",),
          hint="warpctc")
def _warpctc(opctx, attrs, data, label):
    return _warpctc_impl(data, label, int(attrs["input_length"]),
                         int(attrs["label_length"]))

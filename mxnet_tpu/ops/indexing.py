"""Indexing ops: Embedding / take / batch_take / one_hot / pick and the
registered NDArray helpers (_onehot_encode, choose_element_0index,
fill_element_0index).

Parity surface: /root/reference/src/operator/tensor/indexing_op.{h,cc} and
the MXNET_REGISTER_NDARRAY_FUN entries in src/ndarray/ndarray.cc:796+.
Gathers lower to XLA gather/one-hot-matmul; Embedding's gradient is a
scatter-add XLA handles natively (the reference needs AddTakeGrad kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .param import Param, _np_dtype
from .registry import register


def _embedding_infer(attrs, in_shapes):
    data, weight = in_shapes
    w = (attrs["input_dim"], attrs["output_dim"])
    out = None if data is None else tuple(data) + (attrs["output_dim"],)
    return [data, w], [out], []


@register("Embedding", inputs=("data", "weight"),
          params={"input_dim": Param(int, required=True),
                  "output_dim": Param(int, required=True),
                  "dtype": Param("dtype", "float32")},
          infer_shape=_embedding_infer, no_grad_inputs=("data",), hint="embedding")
def _embedding(opctx, attrs, data, weight):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


def embedding_row_sparse_grad(data, out_grad, input_dim):
    """Row-sparse weight gradient for Embedding: the autodiff path scatters
    out_grad into a dense zeros_like(weight) even when |unique(data)| <<
    input_dim; this emits only the touched rows as a RowSparseArray.

    data: integer index array of any shape; out_grad: data.shape +
    (output_dim,).  Allocation is O(touched_rows * output_dim), never
    O(input_dim).  Summation over duplicate indices matches the dense
    scatter-add semantics."""
    from ..sparse.array import RowSparseArray

    data = np.asarray(data).astype(np.int64).reshape(-1)
    out_grad = np.asarray(out_grad)
    dim = out_grad.shape[-1]
    rows = out_grad.reshape(-1, dim)
    if rows.shape[0] != data.shape[0]:
        raise ValueError("out_grad rows %d != index count %d"
                         % (rows.shape[0], data.shape[0]))
    uniq, inverse = np.unique(data, return_inverse=True)
    merged = np.zeros((uniq.shape[0], dim), dtype=rows.dtype)
    np.add.at(merged, inverse, rows)
    return RowSparseArray(uniq, merged, (int(input_dim), dim))


@register("take", inputs=("a", "indices"),
          params={"axis": Param(int, 0),
                  "mode": Param(str, "clip", enum=("clip", "wrap", "raise"))},
          no_grad_inputs=("indices",))
def _take(opctx, attrs, a, indices):
    mode = attrs.get("mode", "clip")
    return jnp.take(a, indices.astype(jnp.int32), axis=attrs.get("axis", 0),
                    mode="wrap" if mode == "wrap" else "clip")


@register("batch_take", inputs=("a", "indices"), no_grad_inputs=("indices",))
def _batch_take(opctx, attrs, a, indices):
    idx = indices.astype(jnp.int32).reshape(-1)
    return a[jnp.arange(a.shape[0]), idx]


def _one_hot_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    return in_shapes, [tuple(ishape) + (attrs["depth"],)], []


@register("one_hot", inputs=("indices",),
          params={"depth": Param(int, required=True), "on_value": Param(float, 1.0),
                  "off_value": Param(float, 0.0), "dtype": Param("dtype", "float32")},
          infer_shape=_one_hot_infer, no_grad_inputs=("indices",))
def _one_hot(opctx, attrs, indices):
    dtype = _np_dtype(attrs.get("dtype", "float32"))
    depth = attrs["depth"]
    on, off = attrs.get("on_value", 1.0), attrs.get("off_value", 0.0)
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype)
    return oh * (on - off) + off


def _pick_infer(attrs, in_shapes):
    data, index = in_shapes
    if data is None:
        return in_shapes, [None], []
    axis = attrs.get("axis", -1) % len(data)
    out = list(data)
    if attrs.get("keepdims", False):
        out[axis] = 1
    else:
        del out[axis]
    return in_shapes, [tuple(out)], []


@register("pick", inputs=("data", "index"),
          params={"axis": Param(int, -1), "keepdims": Param(bool, False)},
          infer_shape=_pick_infer, no_grad_inputs=("index",),
          aliases=("choose_element_0index",))
def _pick(opctx, attrs, data, index):
    axis = attrs.get("axis", -1) % data.ndim
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not attrs.get("keepdims", False):
        out = jnp.squeeze(out, axis=axis)
    return out


@register("fill_element_0index", inputs=("lhs", "mhs", "rhs"),
          no_grad_inputs=("rhs",))
def _fill_element_0index(opctx, attrs, lhs, mhs, rhs):
    """lhs[i, rhs[i]] = mhs[i] (reference: ndarray.cc TernaryOp registration)."""
    idx = rhs.astype(jnp.int32)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


@register("_onehot_encode", inputs=("lhs", "rhs"), no_grad_inputs=("lhs",))
def _onehot_encode(opctx, attrs, lhs, rhs):
    """Write one-hot rows of lhs's indices into rhs's shape (reference:
    ndarray.cc:796+ _onehot_encode(index, out))."""
    depth = rhs.shape[1]
    return jax.nn.one_hot(lhs.astype(jnp.int32), depth, dtype=rhs.dtype)

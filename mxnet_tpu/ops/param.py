"""Declarative op parameters — TPU-native analogue of ``dmlc::Parameter<T>``
structs (reference: src/operator/fully_connected-inl.h:30-40 and every
``*-inl.h``).  Each op registers a spec of typed params with defaults and
docs; values arriving as Python objects or as strings (from graph JSON or
kwargs) are coerced to typed values.  This reflection also powers the
generated docstrings, as the reference's param docs power codegen
(src/c_api/c_api_symbolic.cc:68).
"""
from __future__ import annotations

import ast
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["Param", "parse_attrs", "attrs_to_strs", "DTYPE_MAP"]

DTYPE_MAP = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": "bfloat16",  # resolved lazily to jnp.bfloat16
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def _np_dtype(name):
    if name == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return np.dtype(DTYPE_MAP[name]) if name in DTYPE_MAP else np.dtype(name)


class Param:
    """One typed op parameter.

    ``typ``: one of int, float, bool, 'shape' (tuple of ints), 'float-shape'
    (tuple of floats — no int coercion; use for sizes/ratios/variances/...),
    str, 'dtype', 'float-or-none', 'shape-or-none', 'int-or-none'.
    """

    def __init__(self, typ, default=None, required=False, enum=None, doc=""):
        self.typ = typ
        self.default = default
        self.required = required
        self.enum = enum
        self.doc = doc

    def parse(self, value: Any) -> Any:
        if value is None:
            return None
        t = self.typ
        if t == "shape" or t == "shape-or-none":
            return _parse_shape(value)
        if t == "float-shape":
            return _parse_shape(value, cast=float)
        if t is int or t == "int-or-none":
            if isinstance(value, str):
                if value.lower() == "none":
                    return None
                return int(float(value))
            return int(value)
        if t is float or t == "float-or-none":
            if isinstance(value, str):
                if value.lower() == "none":
                    return None
                return float(value)
            return float(value)
        if t is bool:
            if isinstance(value, str):
                return value.lower() in ("true", "1")
            return bool(value)
        if t == "dtype":
            if isinstance(value, str):
                return value
            if value in (np.float32, float):
                return "float32"
            return np.dtype(value).name
        if t is str:
            v = str(value)
            if self.enum is not None and v not in self.enum:
                raise ValueError(
                    "invalid value %r, expected one of %s" % (v, self.enum)
                )
            return v
        return value


def _parse_shape(value, cast=int):
    if isinstance(value, str):
        value = value.strip()
        if value.lower() in ("none", "()"):
            return tuple() if value == "()" else None
        parsed = ast.literal_eval(value)
        if isinstance(parsed, (int, float)):
            return (cast(parsed),)
        return tuple(cast(x) for x in parsed)
    if isinstance(value, (int, np.integer)):
        return (cast(value),)
    if isinstance(value, (float, np.floating)):
        if cast is not float:
            raise TypeError("expected int or int tuple, got %r" % (value,))
        return (cast(value),)
    if value is None:
        return None
    return tuple(cast(x) for x in value)


def parse_attrs(spec: Optional[Dict[str, Param]], attrs: Dict[str, Any],
                op_name: str = "", allow_extra: bool = False) -> Dict[str, Any]:
    """Coerce raw attrs (strings or python values) against the spec.

    ``allow_extra``: keep unknown attrs as strings instead of rejecting —
    the Custom op forwards arbitrary user kwargs to the CustomOpProp
    constructor as strings (reference: src/operator/custom/custom.cc
    attr_parser passes raw kwargs through to the Python prop)."""
    out: Dict[str, Any] = {}
    spec = spec or {}
    for key, param in spec.items():
        if key in attrs:
            out[key] = param.parse(attrs[key])
        elif param.required:
            raise ValueError(
                "Required parameter %s of %s is missing" % (key, op_name)
            )
        else:
            out[key] = param.default
    # Graph-level attrs (__ctx_group__ etc.) pass through; unknown plain
    # kwargs are rejected like the reference's dmlc::Parameter::Init.
    for key, value in attrs.items():
        if key not in out:
            if key.startswith("__") or key in ("ctx", "name"):
                out[key] = value
            elif allow_extra:
                out[key] = value if isinstance(value, str) else str(value)
            else:
                raise ValueError(
                    "unknown argument %r for operator %s" % (key, op_name))
    return out


def attrs_to_strs(attrs: Dict[str, Any]) -> Dict[str, str]:
    """Stringify typed attrs for JSON graph serialization (format parity with
    reference symbol JSON where every attr is a string)."""
    out = {}
    for key, value in attrs.items():
        if value is None:
            continue
        if isinstance(value, bool):
            out[key] = "True" if value else "False"
        elif isinstance(value, tuple):
            # preserve element types: float-shape params (sizes/ratios/...)
            # must round-trip fractional values through JSON
            out[key] = "(" + ", ".join(str(v) for v in value) + ")"
        else:
            out[key] = str(value)
    return out

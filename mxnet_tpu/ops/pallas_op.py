"""Public user-kernel escape hatch — ``mx.register_pallas_op``.

MXRtc parity, TPU-style: where the reference lets users compile raw CUDA
strings at runtime and call them as ops (/root/reference/src/common/
mxrtc.cc:117-135, ``mx.rtc``), here users hand in a JAX/Pallas function and
get a first-class registered op back — visible as ``mx.sym.<name>`` /
``mx.nd.<name>``, usable in symbols, executors, Module training, and the
fused step, with an optional custom gradient.

    def kernel(attrs, x):          # attrs: parsed op params
        return pl.pallas_call(...)(x)

    mx.register_pallas_op("my_op", kernel,
                          params={"alpha": Param(float, 1.0)})

For training through a non-differentiable ``pallas_call``, supply ``bwd``
(and optionally ``fwd`` for residual control) with ``jax.custom_vjp``
semantics:

    def fwd(attrs, *inputs):   -> (output, residuals)
    def bwd(attrs, residuals, cotangent) -> tuple of input cotangents

``_contrib_FlashAttention`` (ops/attention.py) is registered through this
exact mechanism.
"""
from __future__ import annotations

from typing import Callable, Optional

__all__ = ["register_pallas_op"]


def register_pallas_op(name: str, fn: Callable, bwd: Optional[Callable] = None,
                       fwd: Optional[Callable] = None, inputs=("data",),
                       params=None, infer_shape=None, num_outputs=1,
                       aliases=(), hint=None):
    """Register ``fn(attrs, *arrays)`` as op ``name``.

    Parameters
    ----------
    fn : the kernel wrapper — typically closes over a ``pl.pallas_call``.
        Receives the parsed attr dict first, then the input arrays.
    bwd : optional custom gradient, ``bwd(attrs, residuals, cotangents) ->
        input cotangents`` (cotangents is the bare output cotangent for
        single-output ops).  Without it the op differentiates through
        ``fn`` itself (fine for plain-jnp fns; pallas_call needs ``bwd``).
    fwd : optional ``fwd(attrs, *arrays) -> (out, residuals)``; defaults to
        saving the inputs as residuals.
    inputs / params / infer_shape / num_outputs / aliases : the registry
        surface, identical to internal op registration (ops/registry.py).
    """
    from .registry import register

    if fwd is not None and bwd is None:
        raise ValueError(
            "register_pallas_op: fwd without bwd has no effect — supply "
            "bwd (custom gradient) or drop fwd")

    decorator = register(name, inputs=tuple(inputs), params=dict(params or {}),
                         infer_shape=infer_shape, num_outputs=num_outputs,
                         aliases=tuple(aliases), hint=hint or name.lower())

    if bwd is None:
        def _op(opctx, attrs, *arrays):
            return fn(attrs, *arrays)
    else:
        def _op(opctx, attrs, *arrays):
            import jax

            @jax.custom_vjp
            def run(*arrs):
                return fn(attrs, *arrs)

            def _fwd(*arrs):
                if fwd is not None:
                    return fwd(attrs, *arrs)
                return run(*arrs), arrs

            def _bwd(res, ct):
                out = bwd(attrs, res, ct)
                return tuple(out)

            run.defvjp(_fwd, _bwd)
            return run(*arrays)

    _op.__name__ = "pallas_op_%s" % name
    decorator(_op)

    # late registration: ops registered after package import also appear on
    # the already-generated mx.sym / mx.nd surfaces.  During initial package
    # import those modules regenerate after all ops load, so only refresh
    # ones that are fully imported (avoids a circular import from ops that
    # register at import time, like _contrib_FlashAttention).
    import sys

    pkg = __package__.rsplit(".", 1)[0]
    sym_mod = sys.modules.get(pkg + ".symbol")
    if sym_mod is not None and hasattr(sym_mod, "_init_symbol_module"):
        sym_mod._init_symbol_module()
    nd_mod = sys.modules.get(pkg + ".ndarray")
    if nd_mod is not None and hasattr(nd_mod, "_init_ops"):
        nd_mod._init_ops()
    return _op

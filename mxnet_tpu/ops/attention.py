"""Fused attention — Pallas TPU kernel (new capability; the reference
predates attention, SURVEY.md §5.7).

``flash_attention`` computes exact softmax attention with the
blockwise-online-softmax recurrence entirely in VMEM (the standard
flash-attention schedule): Q tiles stream over the grid, K/V live in VMEM,
the running (m, l, o) accumulators never materialize the [s, s] score
matrix in HBM. Forward is the Pallas kernel; backward is ``custom_vjp``
recompute through the XLA reference implementation (correct, and XLA fuses
it well; a hand-written backward kernel can slot in later without changing
the API).

On non-TPU backends the same kernel runs in Pallas interpret mode, so tests
on the CPU mesh exercise the real kernel logic. Registered in the op
registry as ``_contrib_FlashAttention`` (inputs [b, s, h, d]); also usable
functionally and as ``ulysses_attention(attn_fn=flash_attention)``.
"""
from __future__ import annotations

import functools

import numpy as np

_NEG = -1e30


def _reference_attention(q, k, v, causal, scale):
    """Dense oracle — the single implementation lives in parallel.ring."""
    from ..parallel.ring import local_attention

    return local_attention(q, k, v, causal=causal, scale=scale)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, bq, bk, nk, scale, causal):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [bq, d]
    d = q.shape[-1]
    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(j, carry):
        o, m, l = carry
        kblk = k_ref[0, pl.dslice(j * bk, bk), :].astype(jnp.float32)
        vblk = v_ref[0, pl.dslice(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[:, None] + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o, m_new, l

    if causal:
        # blocks strictly above the diagonal contribute nothing; bound the
        # loop at the current q block's diagonal
        upto = jnp.minimum((qi + 1) * bq + bk - 1, nk * bk) // bk
    else:
        upto = nk
    o, m, l = jax.lax.fori_loop(0, upto, body, (o0, m0, l0))
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq or sk % bk:
        raise ValueError(
            "flash_attention needs seq lengths divisible by block sizes "
            "(%d %% %d, %d %% %d)" % (sq, bq, sk, bk))
    # [b, s, h, d] -> [b*h, s, d]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    nk = sk // bk
    kernel = functools.partial(_flash_kernel, bq=bq, bk=bk, nk=nk,
                               scale=scale, causal=causal)
    try:
        # under shard_map the output must carry the inputs' varying-axis set
        vma = jax.typeof(qt).vma
        out_shape = jax.ShapeDtypeStruct((b * h, sq, d), q.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct((b * h, sq, d), q.dtype)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = 128, block_k: int = 128):
    """Exact fused attention. q, k, v: [batch, seq, heads, head_dim]."""
    import jax

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    interpret = jax.default_backend() != "tpu"

    @jax.custom_vjp
    def run(q, k, v):
        return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)

    def fwd(q, k, v):
        return run(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q, k, v: _reference_attention(q, k, v, causal, scale),
            q, k, v)
        return vjp(g)

    run.defvjp(fwd, bwd)
    return run(q, k, v)


def _register():
    from .param import Param
    from .registry import register

    @register("_contrib_FlashAttention", inputs=("query", "key", "value"),
              params={"causal": Param(bool, False),
                      "scale": Param("float-or-none", None),
                      "block_q": Param(int, 128),
                      "block_k": Param(int, 128)},
              infer_shape=lambda attrs, s: (s, [s[0]], []),
              hint="flashattention")
    def _flash_op(opctx, attrs, query, key, value):
        return flash_attention(query, key, value,
                               causal=attrs.get("causal", False),
                               scale=attrs.get("scale"),
                               block_q=attrs.get("block_q", 128),
                               block_k=attrs.get("block_k", 128))


_register()

"""Fused attention — Pallas TPU kernels, forward AND backward (new
capability; the reference predates attention, SURVEY.md §5.7).

Forward: the standard flash-attention schedule — Q tiles on the grid, K/V
STREAMED block-by-block through VMEM via the grid's innermost dimension
(BlockSpec index maps; nothing is staged whole), online-softmax (m, l, acc)
carried in VMEM scratch across K steps, logsumexp written out for the
backward.

Backward: two Pallas kernels in the flash-v2 style, recomputing P per block
from (Q, K, logsumexp):
  * dQ kernel — grid over Q tiles, K/V streamed innermost,
    dQ += (P ∘ (dO·Vᵀ − Δ))·K with Δ = rowsum(dO ∘ O);
  * dK/dV kernel — grid over K tiles, Q/dO streamed innermost,
    dV += Pᵀ·dO,  dK += (P ∘ (dO·Vᵀ − Δ))ᵀ·Q.
Both run O(s²) time in O(s) memory — sequence length is bounded by HBM,
not VMEM, so ≥16k-token training steps fit on one chip.

On non-TPU backends the same kernels run in Pallas interpret mode, so the
CPU test mesh exercises the real kernel logic. Registered through the
public ``mx.register_pallas_op`` mechanism (its first user) as
``_contrib_FlashAttention`` (inputs [b, s, h, d]); also usable
functionally and as ``ulysses_attention(attn_fn=flash_attention)``.
"""
from __future__ import annotations

import functools

import numpy as np

_NEG = -1e30
# exp2-based softmax: fold log2(e) into the QK scale so the kernel's
# exponentials are exp2 (the VPU's native transcendental; jnp.exp lowers
# to exp2(x*log2e) anyway — folding removes that multiply from the
# bq*bk-element hot loop). The lse written at the boundary stays NATURAL
# log (the ring/backward contract).
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


def _use_exp2():
    """MXTPU_FLASH_EXP2=0 reverts the softmax to natural-exp (A/B switch).
    Read at TRACE time: an already-jitted step keeps the variant it was
    traced with — rebuild the jit (as tools/flash_ab.py's harness does per
    run) for a flip to take effect."""
    import os

    return os.environ.get("MXTPU_FLASH_EXP2", "1") == "1"


def _compiler_params(pltpu):
    """Grid semantics hint (bh/q-tile parallel, stream dim sequential),
    OFF by default: measured on v5e (tools/flash_ab.py, s=8k d=128), the
    hint made the train step ~40% slower and run-to-run erratic when
    combined with the exp2 softmax (20.7 vs 34.3 TFLOP/s at bq=512
    bk=1024); Mosaic's default sequential pipelining double-buffers the
    streamed blocks fine on its own. MXTPU_FLASH_DIMSEM=1 re-enables."""
    import os

    if os.environ.get("MXTPU_FLASH_DIMSEM", "0") != "1":
        return {}
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))}


def _reference_attention(q, k, v, causal, scale):
    """Dense oracle — the single implementation lives in parallel.ring."""
    from ..parallel.ring import local_attention

    return local_attention(q, k, v, causal=causal, scale=scale)


def _pick_block(block, seq):
    """Largest block <= ``block`` that divides ``seq``, halving from the
    requested size. Sequences shorter than the requested block run as one
    whole-sequence block (legal under the Mosaic equal-to-dim rule);
    longer non-divisible sequences raise rather than silently staging an
    unbounded (seq, seq) score tile into VMEM."""
    b = min(block, seq)
    while b > 128 and seq % b:
        b //= 2
    if seq % b:
        if seq <= block:
            return seq
        raise ValueError(
            "flash_attention: sequence length %d is not divisible by any "
            "block size <= %d; pad the sequence or pass block sizes that "
            "divide it" % (seq, block))
    return b


# ---------------------------------------------------------------------------
# forward kernel — K/V streamed over the innermost grid dimension
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, bq, bk, nk, scale, causal, exp2):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: blocks strictly above the diagonal are fully masked — skip
    # their MXU work entirely (the old fori_loop bounded the loop at the
    # diagonal; on a grid the block body is guarded instead)
    live = (j * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _compute():
        # dots stay in the input dtype (bf16 on TPU -> MXU) with f32
        # accumulation; only the softmax state is f32. Scores live in the
        # base-2 domain (scale folded with log2e — see _LOG2E note).
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * (scale * _LOG2E if exp2 else scale)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        expf = jnp.exp2 if exp2 else jnp.exp
        m = m_scr[...]
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = expf(s - m_new[:, None])
        corr = expf(m - m_new)
        l_scr[...] = l_scr[...] * corr + p.sum(axis=-1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[...]
        lsafe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[...] / lsafe[:, None]).astype(o_ref.dtype)
        # back to natural log at the boundary (ring/backward contract)
        if exp2:
            lse_ref[0, 0] = (m_scr[...] + jnp.log2(lsafe)) * _LN2
        else:
            lse_ref[0, 0] = m_scr[...] + jnp.log(lsafe)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (o, lse) with o: [b, s, h, d], lse: [b*h, s] (f32)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    nk = sk // bk
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk, nk=nk,
                               scale=scale, causal=causal, exp2=_use_exp2())
    # lse carries a singleton middle dim so its block's trailing dims
    # (1, bq) satisfy the Mosaic tiling rule (second-to-last equals the
    # array dim, last divisible by 128); squeezed before returning
    try:
        vma = jax.typeof(qt).vma
        out_shape = [jax.ShapeDtypeStruct((b * h, sq, d), q.dtype, vma=vma),
                     jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32,
                                          vma=vma)]
    except (AttributeError, TypeError):
        out_shape = [jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                     jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32)]
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        # bh and q-tile iterations are independent (parallel); the k
        # stream is the sequential dim carrying the softmax state — the
        # semantics let Mosaic overlap the K/V block DMAs with compute
        interpret=interpret,
        **_compiler_params(pltpu),
    )(qt, kt, vt)
    return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse.reshape(b * h, sq)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, bq, bk, nk, scale, causal, exp2):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = (j * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * (scale * _LOG2E if exp2 else scale)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        # p is the same probability either way; only the exponential's
        # base changes (s and lse both carried in the base-2 domain)
        p = (jnp.exp2(s - (lse * _LOG2E)[:, None]) if exp2
             else jnp.exp(s - lse[:, None]))
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(kblk.dtype)
        acc_scr[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, bq, bk, nq, scale,
                    causal, exp2):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kj = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (i * bq + bq - 1 >= kj * bk) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * (scale * _LOG2E if exp2 else scale)
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = (jnp.exp2(s - (lse * _LOG2E)[:, None]) if exp2
             else jnp.exp(s - lse[:, None]))  # [bq, bk]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_precompute(q, o, lse, do):
    """Loop-invariant backward inputs: flattened q/dO layouts, the global
    row lse, and delta_i = rowsum(dO ∘ O) (cheap elementwise+reduce, fused
    by XLA). Split out so callers that sweep many K/V blocks against one Q
    (the ring backward) compute these once, not per block. lse/delta get a
    singleton middle dim so their (1, 1, bq) blocks pass the Mosaic
    trailing-dims tiling rule (see _flash_forward)."""
    import jax.numpy as jnp

    b, sq, h, d = q.shape
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    dot = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    ot = o.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1)
    return (qt, dot, lse.reshape(b * h, 1, sq),
            delta.reshape(b * h, 1, sq))


def _flash_backward(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                    interpret, pre=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    nq, nk = sq // bq, sk // bk
    exp2 = _use_exp2()  # one read: dq and dk/dv kernels share the variant
    if pre is None:
        pre = _flash_bwd_precompute(q, o, lse, do)
    qt, dot, lse3, delta3 = pre
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    from jax.experimental.pallas import tpu as pltpu

    def scratch(shape):
        return pltpu.VMEM(shape, jnp.float32)

    def sds(shape, dtype):
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(qt).vma)
        except (AttributeError, TypeError):
            return jax.ShapeDtypeStruct(shape, dtype)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, bq=bq, bk=bk, nk=nk, scale=scale,
                          causal=causal, exp2=exp2),
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),   # do
            pl.BlockSpec((1, 1, bq), lambda bh, i, j: (bh, 0, i)),   # lse
            pl.BlockSpec((1, 1, bq), lambda bh, i, j: (bh, 0, i)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=sds((b * h, sq, d), q.dtype),
        scratch_shapes=[scratch((bq, d))],
        interpret=interpret,
        **_compiler_params(pltpu),
    )(qt, kt, vt, dot, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, bq=bq, bk=bk, nq=nq, scale=scale,
                          causal=causal, exp2=exp2),
        grid=(b * h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),   # do
            pl.BlockSpec((1, 1, bq), lambda bh, j, i: (bh, 0, i)),   # lse
            pl.BlockSpec((1, 1, bq), lambda bh, j, i: (bh, 0, i)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[sds((b * h, sk, d), k.dtype),
                   sds((b * h, sk, d), v.dtype)],
        scratch_shapes=[scratch((bk, d)), scratch((bk, d))],
        interpret=interpret,
        **_compiler_params(pltpu),
    )(qt, kt, vt, dot, lse3, delta3)

    unflat = lambda t, s: t.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


# ---------------------------------------------------------------------------
# public functional API
# ---------------------------------------------------------------------------


_DEFAULT_BLOCK = 512


def _autotune_blocks(seq_q, seq_k, head_dim, dtype, causal):
    """Tuning-DB winner for this shape family, or None.  The record-mode
    tuning loop lowers the forward kernel per candidate at one head /
    batch 1 (the grid scales linearly in b*h, so the per-candidate
    RANKING is shape-family-wide) and scores by the XLA-cost-analysis
    roofline — CPU-runnable, no chip needed."""
    from .. import autotune

    if not autotune.enabled():
        return None
    key = {"seq_q": int(seq_q), "seq_k": int(seq_k),
           "head_dim": int(head_dim), "dtype": str(dtype),
           "causal": bool(causal)}

    def build(cand):
        import jax

        interpret = jax.default_backend() != "tpu"
        scale = 1.0 / np.sqrt(head_dim)

        def fwd(q, k, v):
            return _flash_forward(q, k, v, causal, scale,
                                  cand["block_q"], cand["block_k"],
                                  interpret)[0]

        sds = jax.ShapeDtypeStruct
        abstract = (sds((1, seq_q, 1, head_dim), dtype),
                    sds((1, seq_k, 1, head_dim), dtype),
                    sds((1, seq_k, 1, head_dim), dtype))
        return jax.jit(fwd), abstract

    def measure(cand):
        import time

        import jax
        import jax.numpy as jnp

        fn, abstract = build(cand)
        args = [jnp.zeros(a.shape, a.dtype) for a in abstract]
        compiled = fn.lower(*args).compile()
        jax.block_until_ready(compiled(*args))
        t0 = time.perf_counter()
        for _ in range(3):
            out = compiled(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 3 * 1e3

    return autotune.get_or_tune(
        "flash_attention", key,
        candidates=autotune.spaces.flash_blocks(seq_q, seq_k),
        build_fn=build, measure_fn=measure, default=None)


def resolve_blocks(block_q, block_k, seq_q, seq_k, head_dim=128,
                   dtype="bfloat16", causal=False):
    """The EFFECTIVE (block_q, block_k) a call runs with: explicit ints
    are respected as-is, None consults the autotuner (winner for this
    shape family when enabled) and falls back to the measured default
    (512/512 — PERF.md's v5e-validated config); either way the result
    is clamped by ``_pick_block``."""
    if block_q is None or block_k is None:
        tuned = None
        try:
            tuned = _autotune_blocks(seq_q, seq_k, head_dim, dtype, causal)
        except Exception:
            tuned = None
        if block_q is None:
            block_q = (tuned or {}).get("block_q", _DEFAULT_BLOCK)
        if block_k is None:
            block_k = (tuned or {}).get("block_k", _DEFAULT_BLOCK)
    return _pick_block(int(block_q), seq_q), _pick_block(int(block_k), seq_k)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q=None, block_k=None):
    """Exact fused attention, Pallas fwd+bwd. q, k, v: [b, seq, heads, d].

    Default 512 blocks: measured on v5e (d=128, s=8k), 512-wide tiles run
    ~3x faster than 128 (the MXU is fed longer contractions and the VPU
    softmax amortizes); blocks are clamped to the sequence length for
    short inputs.  Passing None (the default) consults the autotuner
    (``MXNET_AUTOTUNE``) for this shape family's winner before falling
    back to 512; explicit block sizes are always respected."""
    import jax

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    block_q, block_k = resolve_blocks(block_q, block_k, q.shape[1],
                                      k.shape[1], q.shape[-1], q.dtype,
                                      causal)
    interpret = jax.default_backend() != "tpu"

    @jax.custom_vjp
    def run(q, k, v):
        o, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)
        return o

    def fwd(q, k, v):
        o, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                                interpret)
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        q, k, v, o, lse = res
        return _flash_backward(q, k, v, o, lse, g, causal, scale, block_q,
                               block_k, interpret)

    run.defvjp(fwd, bwd)
    return run(q, k, v)


# ---------------------------------------------------------------------------
# registry op — first user of the public mx.register_pallas_op mechanism
# ---------------------------------------------------------------------------


def _attrs_config(attrs, q, k):
    """(causal, scale, block_q, block_k) for the registered op.  Attrs
    without pinned block sizes resolve through the autotuner (falling
    back to the measured 512 default) — the fwd and bwd kernels see the
    same deterministic resolution for one (attrs, shapes) pair."""
    d = q.shape[-1]
    scale = attrs.get("scale")
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    causal = bool(attrs.get("causal", False))
    bq, bk = resolve_blocks(attrs.get("block_q"), attrs.get("block_k"),
                            q.shape[1], k.shape[1], d, q.dtype, causal)
    return causal, float(scale), bq, bk


def _fa_fn(attrs, query, key, value):
    import jax

    causal, scale, bq, bk = _attrs_config(attrs, query, key)
    interpret = jax.default_backend() != "tpu"
    o, _ = _flash_forward(query, key, value, causal, scale, bq, bk,
                          interpret)
    return o


def _fa_fwd(attrs, query, key, value):
    import jax

    causal, scale, bq, bk = _attrs_config(attrs, query, key)
    interpret = jax.default_backend() != "tpu"
    o, lse = _flash_forward(query, key, value, causal, scale, bq, bk,
                            interpret)
    return o, (query, key, value, o, lse)


def _fa_bwd(attrs, res, ct):
    import jax

    q, k, v, o, lse = res
    causal, scale, bq, bk = _attrs_config(attrs, q, k)
    interpret = jax.default_backend() != "tpu"
    return _flash_backward(q, k, v, o, lse, ct, causal, scale, bq, bk,
                           interpret)


def splash_attention(q, k, v, causal: bool = True, scale=None):
    """Upstream splash-attention backend (jax.experimental.pallas.ops.tpu)
    behind this framework's [b, seq, heads, d] layout — the mature,
    internally-pipelined TPU kernel, offered as an alternative attention
    implementation for A/B against the in-tree flash kernels (PERF.md's
    ceiling reference). Interpret mode off-TPU, so CPU tests exercise the
    real wrapper. Splash applies no logit scaling itself; q is pre-scaled
    here, and gradients flow through splash's own custom vjp."""
    import jax

    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as _sk,
        splash_attention_mask as _mk,
    )

    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    interpret = jax.default_backend() != "tpu"
    mk_one = (_mk.CausalMask((s, s)) if causal
              else _mk.FullMask((s, s)))
    if s % 128:
        # splash's lane constraint: every block dimension must be a
        # multiple of 128 — shorter/odd sequences use the in-tree flash
        # kernels (which clamp blocks to the sequence)
        raise ValueError(
            "splash_attention requires seq_len to be a multiple of 128 "
            "(got %d); use the flash implementation instead" % s)
    kern = _sk.make_splash_mha_single_device(
        mask=_mk.MultiHeadMask([mk_one for _ in range(h)]),
        interpret=interpret)
    import jax.numpy as jnp

    # scale in q's dtype: an np.float64 scalar would upcast bf16 q to
    # f32 and break the kernel's matching-operand-dtype requirement
    qt = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = jax.vmap(kern)(qt, kt, vt)
    return o.transpose(0, 2, 1, 3)


def _register():
    from .pallas_op import register_pallas_op
    from .param import Param
    from .registry import register

    # dogfooding the public user-kernel API — mx.register_pallas_op IS how
    # this framework's own flash attention becomes an op (MXRtc parity,
    # mxrtc.cc:117-135)
    register_pallas_op(
        "_contrib_FlashAttention", _fa_fn, bwd=_fa_bwd, fwd=_fa_fwd,
        inputs=("query", "key", "value"),
        params={"causal": Param(bool, False),
                "scale": Param("float-or-none", None),
                # None = autotuner winner, else the measured 512 default
                "block_q": Param("int-or-none", None),
                "block_k": Param("int-or-none", None)},
        infer_shape=lambda attrs, s: (s, [s[0]], []),
        hint="flashattention")

    # plain registration (no custom fwd/bwd): splash ships its own
    # custom_vjp, so the executor's jax.vjp differentiates through it
    @register("_contrib_SplashAttention",
              inputs=("query", "key", "value"),
              params={"causal": Param(bool, True),
                      "scale": Param("float-or-none", None)},
              infer_shape=lambda attrs, shapes: (shapes, [shapes[0]], []),
              hint="splashattention")
    def _splash_op(opctx, attrs, query, key, value):
        scale = attrs.get("scale")
        return splash_attention(query, key, value,
                                causal=bool(attrs.get("causal", True)),
                                scale=scale)


_register()

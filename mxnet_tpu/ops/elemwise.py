"""Elementwise, scalar, broadcast and reduce op families.

Parity surface: the ``MXNET_OPERATOR_REGISTER_{UNARY,BINARY,BINARY_SCALAR,
BINARY_BROADCAST,REDUCE}`` registrations in /root/reference/src/operator/tensor/
(elemwise_unary_op.cc, elemwise_binary_op.cc, elemwise_binary_scalar_op.cc,
elemwise_binary_broadcast_op.cc, broadcast_reduce_op.h, elemwise_sum.h).
Implementation is pure jax.numpy — XLA fuses these into surrounding matmuls,
which is the TPU-native replacement for the reference's mshadow expression
templates and the tuned CUDA reduce kernels (broadcast_reduce-inl.cuh).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .param import Param
from .registry import register

# ---------------------------------------------------------------------------
# Unary ops
# ---------------------------------------------------------------------------


def _round_away(x):
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def _gamma(x):
    try:
        from jax.scipy.special import gamma as _g

        return _g(x)
    except ImportError:  # pragma: no cover
        from jax.scipy.special import gammaln

        return jnp.exp(gammaln(x))


_UNARY = {
    "abs": jnp.abs,
    "arccos": jnp.arccos,
    "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin,
    "arcsinh": jnp.arcsinh,
    "arctan": jnp.arctan,
    "arctanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "degrees": jnp.degrees,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "fix": jnp.trunc,
    "floor": jnp.floor,
    "gamma": _gamma,
    "gammaln": lambda x: jax.scipy.special.gammaln(x),
    "log": jnp.log,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "negative": jnp.negative,
    "radians": jnp.radians,
    "rint": jnp.rint,
    "round": _round_away,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "sigmoid": jax.nn.sigmoid,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "tan": jnp.tan,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    # transformer-era additions (post-0.9 mxnet names; the model zoo's
    # transformer family uses gelu)
    "erf": lambda x: jax.scipy.special.erf(x),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
}


def _register_unary(name, jfn, aliases=()):
    @register(name, inputs=("data",), aliases=aliases, hint=name.lstrip("_"))
    def _fn(opctx, attrs, x, _jfn=jfn):
        return _jfn(x)


for _name, _jfn in _UNARY.items():
    _register_unary(_name, _jfn)


@register("_copy", aliases=("identity", "_copyto"), hint="copy")
def _copy(opctx, attrs, x):
    return x


@register("_CrossDeviceCopy", hint="crossdevicecopy")
def _cross_device_copy(opctx, attrs, x):
    """Identity at the op level: the reference splices this node at ctx
    boundaries (src/operator/cross_device_copy.cc) and its engine moves the
    bytes; here the executor's placement map compiles the device transfer
    (jax.device_put) into the step, so graphs loaded from reference JSON
    that contain this node run unchanged."""
    return x


def _broadcast_fun_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    out = list(d)
    out[attrs["axis"]] = attrs["size"]
    return in_shapes, [tuple(out)], []


@register("_broadcast", params={"axis": Param(int, required=True),
                                "size": Param(int, required=True)},
          infer_shape=_broadcast_fun_infer, hint="broadcastfun")
def _broadcast_fun(opctx, attrs, x):
    """Registered NDArray function ``_broadcast`` (reference
    src/ndarray/ndarray.cc:898: "Broadcast array in the given axis to the
    given size"; the size-1 axis expands).  Call with keyword params:
    ``mx.nd._broadcast(x, axis=0, size=4)``."""
    axis, size = attrs["axis"], attrs["size"]
    shape = list(x.shape)
    shape[axis] = size
    return jnp.broadcast_to(x, tuple(shape))


@register("BlockGrad", aliases=("stop_gradient",), hint="blockgrad")
def _block_grad(opctx, attrs, x):
    return jax.lax.stop_gradient(x)


def _make_loss_fn():
    @jax.custom_vjp
    def _ml(x, grad_scale):
        return x

    def _fwd(x, grad_scale):
        return x, (jnp.shape(x), x.dtype, grad_scale)

    def _bwd(res, ct):
        shape, dtype, grad_scale = res
        # Reference semantics (make_loss, elemwise_unary_op.cc): the backward
        # value is grad_scale regardless of the head gradient.
        del ct
        return jnp.full(shape, grad_scale, dtype), None

    _ml.defvjp(_fwd, _bwd)
    return _ml


_make_loss_impl = _make_loss_fn()


@register("make_loss", params={"grad_scale": Param(float, 1.0)}, hint="make_loss")
def _make_loss(opctx, attrs, x):
    return _make_loss_impl(x, attrs.get("grad_scale", 1.0))


@register("softmax", params={"axis": Param(int, -1), "temperature": Param("float-or-none", None)})
def _softmax(opctx, attrs, x):
    t = attrs.get("temperature")
    if t:
        x = x / t
    return jax.nn.softmax(x, axis=attrs.get("axis", -1))


@register("log_softmax", params={"axis": Param(int, -1), "temperature": Param("float-or-none", None)})
def _log_softmax(opctx, attrs, x):
    t = attrs.get("temperature")
    if t:
        x = x / t
    return jax.nn.log_softmax(x, axis=attrs.get("axis", -1))


@register("smooth_l1", params={"scalar": Param(float, 1.0)})
def _smooth_l1(opctx, attrs, x):
    # f(x) = 0.5 (sx)^2 if |x| < 1/s^2 else |x| - 0.5/s^2
    # (reference: elemwise_unary_op.cc smooth_l1, used by RCNN examples)
    s = attrs.get("scalar", 1.0)
    s2 = s * s
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


# ---------------------------------------------------------------------------
# Binary elementwise (same-shape) + comparison
# ---------------------------------------------------------------------------

_BINARY = {
    "elemwise_add": jnp.add,
    "elemwise_sub": jnp.subtract,
    "elemwise_mul": jnp.multiply,
    "elemwise_div": jnp.divide,
    "_grad_add": jnp.add,
    "_power": jnp.power,
    "_maximum": jnp.maximum,
    "_minimum": jnp.minimum,
    "_hypot": jnp.hypot,
    "_mod": jnp.mod,
}

_BINARY_ALIASES = {
    "elemwise_add": ("_add", "_plus", "_Plus"),
    "elemwise_sub": ("_sub", "_minus", "_Minus"),
    "elemwise_mul": ("_mul", "_Mul"),
    "elemwise_div": ("_div", "_Div"),
    "_power": ("_Power", "pow"),
    "_maximum": ("_Maximum",),
    "_minimum": ("_Minimum",),
    "_mod": ("_Mod",),
}

_COMPARE = {
    "_equal": jnp.equal,
    "_not_equal": jnp.not_equal,
    "_greater": jnp.greater,
    "_greater_equal": jnp.greater_equal,
    "_lesser": jnp.less,
    "_lesser_equal": jnp.less_equal,
}


def _register_binary(name, jfn, aliases=(), compare=False):
    @register(name, inputs=("lhs", "rhs"), aliases=aliases, hint=name.lstrip("_"))
    def _fn(opctx, attrs, lhs, rhs, _jfn=jfn, _cmp=compare):
        out = _jfn(lhs, rhs)
        if _cmp:
            # Reference comparison ops keep the input dtype (pre-bool era).
            out = out.astype(jnp.result_type(lhs, rhs))
        return out


for _name, _jfn in _BINARY.items():
    _register_binary(_name, _jfn, _BINARY_ALIASES.get(_name, ()))
for _name, _jfn in _COMPARE.items():
    _register_binary(_name, _jfn, (_name[1:].title().replace("_", ""),), compare=True)


# ---------------------------------------------------------------------------
# Scalar variants
# ---------------------------------------------------------------------------

_SCALAR_SPEC = {"scalar": Param(float, required=True)}

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, s),
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}

_SCALAR_ALIASES = {
    "_plus_scalar": ("_PlusScalar",),
    "_minus_scalar": ("_MinusScalar",),
    "_rminus_scalar": ("_RMinusScalar",),
    "_mul_scalar": ("_MulScalar",),
    "_div_scalar": ("_DivScalar",),
    "_rdiv_scalar": ("_RDivScalar",),
    "_power_scalar": ("_PowerScalar",),
    "_rpower_scalar": ("_RPowerScalar",),
    "_maximum_scalar": ("_MaximumScalar",),
    "_minimum_scalar": ("_MinimumScalar",),
}


def _register_scalar(name, jfn, aliases=()):
    @register(name, inputs=("data",), params=dict(_SCALAR_SPEC), aliases=aliases,
              hint=name.lstrip("_"))
    def _fn(opctx, attrs, x, _jfn=jfn):
        return _jfn(x, attrs["scalar"])


for _name, _jfn in _SCALAR.items():
    _register_scalar(_name, _jfn, _SCALAR_ALIASES.get(_name, ()))


# ---------------------------------------------------------------------------
# Broadcast binary family
# ---------------------------------------------------------------------------

_BROADCAST = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
}

_BROADCAST_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
}

_BROADCAST_ALIASES = {
    "broadcast_add": ("broadcast_plus",),
    "broadcast_sub": ("broadcast_minus",),
}

for _name, _jfn in _BROADCAST.items():
    _register_binary(_name, _jfn, _BROADCAST_ALIASES.get(_name, ()))
for _name, _jfn in _BROADCAST_CMP.items():
    _register_binary(_name, _jfn, compare=True)


def _infer_broadcast_axis(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    axes = attrs.get("axis") or ()
    sizes = attrs.get("size") or ()
    if isinstance(axes, int):
        axes = (axes,)
    if isinstance(sizes, int):
        sizes = (sizes,)
    out = list(ishape)
    for ax, sz in zip(axes, sizes):
        out[ax] = sz
    return in_shapes, [tuple(out)], []


@register("broadcast_axis", params={"axis": Param("shape", ()), "size": Param("shape", ())},
          aliases=("broadcast_axes",), infer_shape=_infer_broadcast_axis)
def _broadcast_axis(opctx, attrs, x):
    axes = attrs.get("axis") or ()
    sizes = attrs.get("size") or ()
    if isinstance(axes, int):
        axes = (axes,)
    if isinstance(sizes, int):
        sizes = (sizes,)
    shape = list(x.shape)
    for ax, sz in zip(axes, sizes):
        shape[ax] = sz
    return jnp.broadcast_to(x, tuple(shape))


def _infer_broadcast_to(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    tgt = list(attrs.get("shape") or ())
    for i, s in enumerate(tgt):
        if s == 0:
            tgt[i] = ishape[i]
    return in_shapes, [tuple(tgt)], []


@register("broadcast_to", params={"shape": Param("shape", ())},
          infer_shape=_infer_broadcast_to)
def _broadcast_to(opctx, attrs, x):
    tgt = list(attrs.get("shape") or ())
    for i, s in enumerate(tgt):
        if s == 0:
            tgt[i] = x.shape[i]
    return jnp.broadcast_to(x, tuple(tgt))


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------

_REDUCE_SPEC = {
    "axis": Param("shape-or-none", None),
    "keepdims": Param(bool, False),
    "exclude": Param(bool, False),
}


def _norm_axis(attrs, ndim):
    axis = attrs.get("axis")
    if axis is None or axis == ():
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if attrs.get("exclude"):
        axis = tuple(i for i in range(ndim) if i not in axis)
    return axis


def _reduce_out_shape(ishape, axis, keepdims):
    if axis is None:
        return (1,) * len(ishape) if keepdims else ()
    out = list(ishape)
    for a in sorted(axis, reverse=True):
        if keepdims:
            out[a] = 1
        else:
            del out[a]
    return tuple(out)


def _make_reduce_infer():
    def infer(attrs, in_shapes):
        (ishape,) = in_shapes
        if ishape is None:
            return in_shapes, [None], []
        axis = _norm_axis(attrs, len(ishape))
        return in_shapes, [_reduce_out_shape(ishape, axis, attrs.get("keepdims", False))], []

    return infer


_REDUCE = {
    "sum": jnp.sum,
    "mean": jnp.mean,
    "prod": jnp.prod,
    "nansum": jnp.nansum,
    "nanprod": jnp.nanprod,
    "max": jnp.max,
    "min": jnp.min,
}

_REDUCE_ALIASES = {"sum": ("sum_axis",), "max": ("max_axis",), "min": ("min_axis",)}


def _register_reduce(name, jfn, aliases=()):
    @register(name, inputs=("data",), params=dict(_REDUCE_SPEC), aliases=aliases,
              infer_shape=_make_reduce_infer(), hint=name)
    def _fn(opctx, attrs, x, _jfn=jfn):
        axis = _norm_axis(attrs, x.ndim)
        return _jfn(x, axis=axis, keepdims=attrs.get("keepdims", False))


for _name, _jfn in _REDUCE.items():
    _register_reduce(_name, _jfn, _REDUCE_ALIASES.get(_name, ()))


_ARG_SPEC = {"axis": Param("int-or-none", None), "keepdims": Param(bool, False)}


def _register_argreduce(name, jfn):
    def infer(attrs, in_shapes):
        (ishape,) = in_shapes
        if ishape is None:
            return in_shapes, [None], []
        axis = attrs.get("axis")
        kd = attrs.get("keepdims", False)
        ax = None if axis is None else (axis % len(ishape),)
        return in_shapes, [_reduce_out_shape(ishape, ax, kd)], []

    @register(name, inputs=("data",), params=dict(_ARG_SPEC), infer_shape=infer)
    def _fn(opctx, attrs, x, _jfn=jfn):
        axis = attrs.get("axis")
        # Reference returns float indices (pre-integer-dtype era,
        # broadcast_reduce_op.h) — keep for parity.
        out = _jfn(x, axis=axis)
        if attrs.get("keepdims", False) and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.float32 if x.dtype == jnp.float64 else x.dtype)


_register_argreduce("argmax", jnp.argmax)
_register_argreduce("argmin", jnp.argmin)


@register("argmax_channel")
def _argmax_channel(opctx, attrs, x):
    return jnp.argmax(x, axis=1).astype(x.dtype)


@register("norm", infer_shape=lambda attrs, s: (s, [(1,)], []))
def _norm(opctx, attrs, x):
    return jnp.sqrt(jnp.sum(jnp.square(x))).reshape((1,))


# ---------------------------------------------------------------------------
# N-ary sum (ElementWiseSum / add_n — reference src/operator/tensor/elemwise_sum.h)
# ---------------------------------------------------------------------------


@register("add_n", key_var_num_args="num_args", inputs=("data",),
          params={"num_args": Param(int, required=True)},
          aliases=("ElementWiseSum", "_sum"), hint="add_n")
def _add_n(opctx, attrs, *args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out

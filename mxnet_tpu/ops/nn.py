"""Neural-network layer ops — the legacy ``MXNET_REGISTER_OP_PROPERTY`` layer
surface of the reference, re-built on jax.numpy / lax so XLA owns fusion and
MXU mapping (replacing mshadow expressions + cuDNN dispatch, e.g.
/root/reference/src/operator/fully_connected-inl.h:81,
src/operator/convolution.cu:18-44).

Loss "Output" ops reproduce the reference's backward semantics exactly via
``jax.custom_vjp`` (they ignore head gradients — they ARE the loss):
  * SoftmaxOutput:  grad = (softmax - onehot) * grad_scale / normalizer,
    ignore_label masking (src/operator/softmax_output-inl.h:106-220)
  * {Linear,Logistic,MAE}RegressionOutput: grad = grad_scale / num_output *
    BackwardOp(out, label) (src/operator/regression_output-inl.h:56-80)
  * MakeLoss: grad = grad_scale (src/operator/make_loss-inl.h)
  * SVMOutput: hinge-loss grad (src/operator/svm_output-inl.h)

Layer params (kernel/stride/pad tuples, NCHW layouts, fix_gamma defaults)
match the reference's dmlc::Parameter declarations so graph JSON and script
kwargs carry over unchanged.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .param import Param, _np_dtype
from .registry import register

# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


@register("Activation",
          params={"act_type": Param(str, required=True,
                                    enum=("relu", "sigmoid", "tanh", "softrelu"))},
          hint="activation")
def _activation(opctx, attrs, x):
    t = attrs["act_type"]
    if t == "relu":
        return jax.nn.relu(x)
    if t == "sigmoid":
        return jax.nn.sigmoid(x)
    if t == "tanh":
        return jnp.tanh(x)
    return jax.nn.softplus(x)  # softrelu


def _leaky_inputs(attrs):
    if attrs.get("act_type", "leaky") == "prelu":
        return ["data", "gamma"]
    return ["data"]


def _leaky_infer(attrs, in_shapes):
    d = in_shapes[0]
    if attrs.get("act_type", "leaky") == "prelu":
        g = (d[1],) if d is not None else in_shapes[1]
        return [d, g], [d], []
    return in_shapes, [d], []


@register("LeakyReLU", inputs=_leaky_inputs,
          params={"act_type": Param(str, "leaky", enum=("rrelu", "leaky", "prelu", "elu")),
                  "slope": Param(float, 0.25),
                  "lower_bound": Param(float, 0.125), "upper_bound": Param(float, 0.334)},
          infer_shape=_leaky_infer, stochastic=True, hint="leakyrelu")
def _leaky_relu(opctx, attrs, x, *rest):
    t = attrs.get("act_type", "leaky")
    if t == "leaky":
        return jnp.where(x > 0, x, attrs.get("slope", 0.25) * x)
    if t == "elu":
        s = attrs.get("slope", 0.25)
        return jnp.where(x > 0, x, s * jnp.expm1(x))
    if t == "prelu":
        gamma = rest[0].reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, gamma * x)
    # rrelu: random slope in train, mean slope in eval
    lo, up = attrs.get("lower_bound", 0.125), attrs.get("upper_bound", 0.334)
    if opctx.is_train and opctx.rng is not None:
        slope = jax.random.uniform(opctx.rng, x.shape, x.dtype, lo, up)
    else:
        slope = (lo + up) / 2.0
    return jnp.where(x > 0, x, slope * x)


# ---------------------------------------------------------------------------
# FullyConnected — dot(data, W^T) + b on the MXU
# ---------------------------------------------------------------------------


def _fc_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"]


def _fc_infer(attrs, in_shapes):
    d = in_shapes[0]
    nh = attrs["num_hidden"]
    if d is None:
        return in_shapes, [None], []
    if attrs.get("flatten", True) or len(d) <= 2:
        in_dim = int(np.prod(d[1:])) if len(d) > 1 else 1
        out = (d[0], nh)
    else:
        # flatten=False: FC applies to the trailing axis only (reference
        # fully_connected-inl.h flatten param)
        in_dim = d[-1]
        out = tuple(d[:-1]) + (nh,)
    shapes = [d, (nh, in_dim)]
    if not attrs.get("no_bias"):
        shapes.append((nh,))
    return shapes, [out], []


@register("FullyConnected", inputs=_fc_inputs,
          params={"num_hidden": Param(int, required=True), "no_bias": Param(bool, False),
                  "flatten": Param(bool, True)},
          infer_shape=_fc_infer, hint="fullyconnected")
def _fully_connected(opctx, attrs, data, weight, *rest):
    if data.ndim > 2 and attrs.get("flatten", True):
        data = data.reshape(data.shape[0], -1)
    out = jnp.dot(data, weight.T)
    if rest:
        out = out + rest[0]
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

_CONV_SPEC = {
    "kernel": Param("shape", required=True),
    "stride": Param("shape", ()),
    "dilate": Param("shape", ()),
    "pad": Param("shape", ()),
    "num_filter": Param(int, required=True),
    "num_group": Param(int, 1),
    "workspace": Param(int, 1024),
    "no_bias": Param(bool, False),
    "cudnn_tune": Param(str, ""),
    "cudnn_off": Param(bool, False),
    "layout": Param(str, ""),
}


def _conv_inputs(attrs):
    return ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"]


def _tup(v, nd, default):
    if not v:
        return (default,) * nd
    return tuple(v)


def _conv_out_dim(x, k, s, p, d):
    return (x + 2 * p - (d * (k - 1) + 1)) // s + 1


def _conv_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride = _tup(attrs.get("stride"), nd, 1)
    pad = _tup(attrs.get("pad"), nd, 0)
    dil = _tup(attrs.get("dilate"), nd, 1)
    nf, ng = attrs["num_filter"], attrs.get("num_group", 1)
    wshape = (nf, data[1] // ng) + tuple(kernel)
    shapes = [data, wshape] + ([] if attrs.get("no_bias") else [(nf,)])
    spatial = tuple(
        _conv_out_dim(data[2 + i], kernel[i], stride[i], pad[i], dil[i])
        for i in range(nd)
    )
    return shapes, [(data[0], nf) + spatial], []


def _conv_dnums(nd):
    spec = "NCHW"[: 2 + nd] if nd <= 2 else "NCDHW"
    lhs = "NC" + "DHW"[-nd:]
    out = lhs
    rhs = "OI" + "DHW"[-nd:]
    del spec
    return lax.conv_dimension_numbers((1, 1) + (1,) * nd, (1, 1) + (1,) * nd,
                                      (lhs, rhs, out))


@register("Convolution", inputs=_conv_inputs, params=dict(_CONV_SPEC),
          infer_shape=_conv_infer, aliases=("Convolution_v1",), hint="convolution")
def _convolution(opctx, attrs, data, weight, *rest):
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride = _tup(attrs.get("stride"), nd, 1)
    pad = _tup(attrs.get("pad"), nd, 0)
    dil = _tup(attrs.get("dilate"), nd, 1)
    dn = _conv_dnums(nd)
    # no preferred_element_type upcast: the MXU accumulates bf16 matmuls in
    # f32 internally, and an explicit f32 output breaks the conv transpose
    # rule under vjp (cotangent f32 vs bf16 operands)
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dil,
        dimension_numbers=dn, feature_group_count=attrs.get("num_group", 1),
    )
    if rest:
        bias = rest[0].reshape((1, -1) + (1,) * nd)
        out = out + bias
    return out


_DECONV_SPEC = dict(_CONV_SPEC)
_DECONV_SPEC.update({
    "adj": Param("shape", ()),
    "target_shape": Param("shape", ()),
})


def _deconv_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride = _tup(attrs.get("stride"), nd, 1)
    pad = _tup(attrs.get("pad"), nd, 0)
    adj = _tup(attrs.get("adj"), nd, 0)
    nf, ng = attrs["num_filter"], attrs.get("num_group", 1)
    wshape = (data[1], nf // ng) + tuple(kernel)
    shapes = [data, wshape] + ([] if attrs.get("no_bias") else [(nf,)])
    tgt = attrs.get("target_shape")
    if tgt:
        spatial = tuple(tgt)
    else:
        spatial = tuple(
            stride[i] * (data[2 + i] - 1) + kernel[i] - 2 * pad[i] + adj[i]
            for i in range(nd)
        )
    return shapes, [(data[0], nf) + spatial], []


@register("Deconvolution", inputs=_conv_inputs, params=dict(_DECONV_SPEC),
          infer_shape=_deconv_infer, hint="deconvolution")
def _deconvolution(opctx, attrs, data, weight, *rest):
    """Transposed convolution: lhs-dilated conv with the flipped, IO-swapped
    kernel (reference: src/operator/deconvolution-inl.h — implemented there as
    the backward of Convolution)."""
    kernel = attrs["kernel"]
    nd = len(kernel)
    stride = _tup(attrs.get("stride"), nd, 1)
    pad = _tup(attrs.get("pad"), nd, 0)
    adj = _tup(attrs.get("adj"), nd, 0)
    ng = attrs.get("num_group", 1)
    nf = attrs["num_filter"]
    c = data.shape[1]
    # weight (C, F/g, *k) -> grouped OIHW (F, C/g, *k), spatially flipped
    w = weight.reshape((ng, c // ng, nf // ng) + tuple(kernel))
    w = jnp.swapaxes(w, 1, 2).reshape((nf, c // ng) + tuple(kernel))
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    dn = _conv_dnums(nd)
    padding = [
        (kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i]) for i in range(nd)
    ]
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, dimension_numbers=dn, feature_group_count=ng,
    )
    if rest:
        out = out + rest[0].reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

_POOL_SPEC = {
    "kernel": Param("shape", required=True),
    "pool_type": Param(str, "max", enum=("max", "avg", "sum")),
    "global_pool": Param(bool, False),
    "pooling_convention": Param(str, "valid", enum=("valid", "full")),
    "stride": Param("shape", ()),
    "pad": Param("shape", ()),
    "cudnn_off": Param(bool, False),
}


def _pool_out_dim(x, k, s, p, full):
    if full:
        return int(np.ceil((x + 2 * p - k) / s)) + 1
    return (x + 2 * p - k) // s + 1


def _pool_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    nd = len(data) - 2
    if attrs.get("global_pool"):
        return in_shapes, [tuple(data[:2]) + (1,) * nd], []
    kernel = attrs["kernel"]
    stride = _tup(attrs.get("stride"), nd, 1)
    pad = _tup(attrs.get("pad"), nd, 0)
    full = attrs.get("pooling_convention", "valid") == "full"
    spatial = tuple(
        _pool_out_dim(data[2 + i], kernel[i], stride[i], pad[i], full)
        for i in range(nd)
    )
    return in_shapes, [tuple(data[:2]) + spatial], []


@register("Pooling", params=dict(_POOL_SPEC), infer_shape=_pool_infer,
          aliases=("Pooling_v1",), hint="pooling")
def _pooling(opctx, attrs, x):
    nd = x.ndim - 2
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool"):
        kernel = x.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = tuple(attrs["kernel"])
        stride = _tup(attrs.get("stride"), nd, 1)
        pad = _tup(attrs.get("pad"), nd, 0)
    full = attrs.get("pooling_convention", "valid") == "full"
    # explicit padding achieving the reference's output-size convention
    pads = []
    for i in range(nd):
        out = _pool_out_dim(x.shape[2 + i], kernel[i], stride[i], pad[i], full)
        need = max((out - 1) * stride[i] + kernel[i] - x.shape[2 + i], 0)
        pads.append((pad[i], max(need - pad[i], 0)))
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = [(0, 0), (0, 0)] + pads
    # init values must be Python/numpy scalar literals: under jit a traced
    # jnp.array init stops lax from recognizing the max/add monoid and routes
    # to the generic (non-differentiable) reduce_window primitive
    if ptype == "max":
        init = (np.array(-np.inf, x.dtype) if jnp.issubdtype(x.dtype, jnp.floating)
                else np.array(np.iinfo(x.dtype).min, x.dtype))
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    summed = lax.reduce_window(x, np.array(0, x.dtype), lax.add, window,
                               strides, padding)
    if ptype == "sum":
        return summed
    # avg: reference divides by full window size (count_include_pad semantics
    # of mshadow pool, src/operator/pooling-inl.h)
    return summed / np.prod(kernel)


# ---------------------------------------------------------------------------
# BatchNorm — aux states (moving_mean/moving_var) threaded functionally
# ---------------------------------------------------------------------------


def _bn_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None, None, None], []
    c = (d[1] if len(d) > 1 else d[0],)
    nout = 3 if attrs.get("output_mean_var") else 1
    outs = [tuple(d)] + ([c, c] if nout == 3 else [])
    return [d, c, c], outs, [c, c]


@register("BatchNorm", inputs=("data", "gamma", "beta"),
          aux=("moving_mean", "moving_var"),
          params={"eps": Param(float, 1e-3), "momentum": Param(float, 0.9),
                  "fix_gamma": Param(bool, True), "use_global_stats": Param(bool, False),
                  "output_mean_var": Param(bool, False)},
          num_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
          infer_shape=_bn_infer, aliases=("CuDNNBatchNorm",), hint="batchnorm",
          aux_dtype="float32")
def _batch_norm(opctx, attrs, data, gamma, beta, moving_mean, moving_var):
    eps = attrs.get("eps", 1e-3)
    momentum = attrs.get("momentum", 0.9)
    fix_gamma = attrs.get("fix_gamma", True)
    use_global = attrs.get("use_global_stats", False) or not opctx.is_train
    axes = (0,) + tuple(range(2, data.ndim))
    bshape = (1, -1) + (1,) * (data.ndim - 2) if data.ndim > 1 else (-1,)
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    # statistics accumulate in f32 regardless of compute dtype (bf16
    # mean/var over a large batch loses precision), but WITHOUT materializing
    # an f32 copy of the activation: the convert fuses into each reduction's
    # input, so data is only ever read from HBM in bf16.  E[x^2]-E[x]^2 is
    # safe here because conv outputs are ~zero-mean (and the subtraction is
    # f32).
    if use_global:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    else:
        mean = jnp.mean(data, axis=axes, dtype=jnp.float32)
        if data.dtype == jnp.float32:
            # full precision in, full precision stats: two-pass centered
            # variance (no E[x^2]-E[x]^2 cancellation for large-mean data)
            var = jnp.mean(jnp.square(data - mean.reshape(bshape)),
                           axis=axes)
        else:
            # mixed-precision hot path (ResNet bench): one-pass f32-
            # accumulated E[x^2]-E[x]^2 lets XLA compute both stats in a
            # single multi-output reduce fusion (one HBM read of the
            # activation instead of two).  Cancellation needs |mean|>>std to
            # matter, which bf16 inputs (8-bit mantissa) cannot represent
            # more precisely than this subtraction resolves.
            meansq = jnp.mean(jnp.square(data.astype(jnp.float32)),
                              axis=axes)
            var = jnp.maximum(meansq - jnp.square(mean), 0.0)
        new_mm = momentum * moving_mean + (1 - momentum) * lax.stop_gradient(mean)
        new_mv = momentum * moving_var + (1 - momentum) * lax.stop_gradient(var)
    inv = lax.rsqrt(var + eps)
    g32 = gamma.astype(jnp.float32)
    scale = (g32 * inv).astype(data.dtype).reshape(bshape)
    shift = (beta.astype(jnp.float32) - mean * inv * g32).astype(
        data.dtype).reshape(bshape)
    out = data * scale + shift
    if attrs.get("output_mean_var"):
        return (out, mean.astype(data.dtype), var.astype(data.dtype),
                new_mm, new_mv)
    return out, new_mm, new_mv


@register("InstanceNorm", inputs=("data", "gamma", "beta"),
          params={"eps": Param(float, 1e-3)},
          infer_shape=lambda attrs, s: (
              [s[0], (s[0][1],), (s[0][1],)] if s[0] is not None else s,
              [s[0]], []),
          hint="instancenorm")
def _instance_norm(opctx, attrs, data, gamma, beta):
    eps = attrs.get("eps", 1e-3)
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    out = (data - mean) * lax.rsqrt(var + eps)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


def _layer_norm_infer(attrs, in_shapes):
    d = in_shapes[0]
    n_out = 3 if attrs.get("output_mean_var") else 1
    if d is None:
        return in_shapes, [None] * n_out, []
    axis = int(attrs.get("axis", -1))
    n = d[axis if axis >= 0 else len(d) + axis]
    outs = [tuple(d)]
    if attrs.get("output_mean_var"):
        red = tuple(v for i, v in enumerate(d)
                    if i != (axis if axis >= 0 else len(d) + axis))
        outs += [red, red]
    return [tuple(d), (n,), (n,)], outs, []


@register("LayerNorm", inputs=("data", "gamma", "beta"),
          params={"axis": Param(int, -1), "eps": Param(float, 1e-5),
                  "output_mean_var": Param(bool, False)},
          num_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
          infer_shape=_layer_norm_infer, hint="layernorm")
def _layer_norm(opctx, attrs, data, gamma, beta):
    """Layer normalization over one axis (post-0.9 mxnet op name; the
    transformer model family's normalization). Statistics in f32 even for
    bf16 activations, like BatchNorm above."""
    eps = attrs.get("eps", 1e-5)
    axis = int(attrs.get("axis", -1))
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    norm = ((x - mean) * lax.rsqrt(var + eps)).astype(data.dtype)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = norm * gamma.reshape(bshape).astype(data.dtype) \
        + beta.reshape(bshape).astype(data.dtype)
    if attrs.get("output_mean_var"):
        # upstream's third output is the standard deviation, not the
        # variance (mxnet layer_norm-inl.h contract: out, mean, std)
        return (out, jnp.squeeze(mean, axis).astype(data.dtype),
                jnp.squeeze(jnp.sqrt(var + eps), axis).astype(data.dtype))
    return out


@register("L2Normalization",
          params={"eps": Param(float, 1e-10),
                  "mode": Param(str, "instance", enum=("instance", "spatial", "channel"))},
          hint="l2normalization")
def _l2_normalization(opctx, attrs, x):
    eps = attrs.get("eps", 1e-10)
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


@register("LRN", params={"alpha": Param(float, 1e-4), "beta": Param(float, 0.75),
                         "knorm": Param(float, 2.0), "nsize": Param(int, required=True)},
          hint="lrn")
def _lrn(opctx, attrs, x):
    """Cross-channel local response norm (reference: src/operator/lrn-inl.h)."""
    nsize = attrs["nsize"]
    alpha, beta, knorm = attrs.get("alpha", 1e-4), attrs.get("beta", 0.75), attrs.get("knorm", 2.0)
    sq = jnp.square(x)
    half = nsize // 2
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, half)
    window = [1] * x.ndim
    window[1] = nsize
    ssum = lax.reduce_window(sq, jnp.array(0, x.dtype), lax.add, tuple(window),
                             (1,) * x.ndim, pads)
    return x * jnp.power(knorm + alpha / nsize * ssum, -beta)


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------


@register("Dropout", params={"p": Param(float, 0.5)}, stochastic=True, hint="dropout")
def _dropout(opctx, attrs, x):
    p = attrs.get("p", 0.5)
    if not opctx.is_train or p <= 0.0 or opctx.rng is None:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(opctx.rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Loss output ops (custom vjp; ignore head gradients)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output_impl(data, label, grad_scale, ignore_label, multi_output,
                         use_ignore, normalization):
    axis = 1 if multi_output else -1
    return jax.nn.softmax(data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, normalization):
    out = jax.nn.softmax(data, axis=1 if multi_output else -1)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, multi_output, use_ignore,
                        normalization, res, ct):
    del ct  # loss op: head gradient ignored (softmax_output-inl.h:131)
    out, label = res
    axis = 1 if multi_output else -1
    nclass = out.shape[axis]
    ilabel = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(ilabel, nclass, dtype=out.dtype, axis=axis)
    grad = out - onehot
    valid = jnp.ones(label.shape, out.dtype)
    if use_ignore:
        valid = (label != ignore_label).astype(out.dtype)
        grad = grad * jnp.expand_dims(valid, axis if multi_output else -1)
    if normalization == "batch":
        norm = label.shape[0]
    elif normalization == "valid":
        norm = jnp.maximum(jnp.sum(valid), 1.0)
    else:
        norm = 1.0
    grad = grad * (grad_scale / norm)
    return grad.astype(out.dtype), jnp.zeros_like(label)


_softmax_output_impl.defvjp(_softmax_output_fwd, _softmax_output_bwd)


def _softmax_label_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    if attrs.get("multi_output"):
        lshape = (d[0],) + tuple(d[2:])
    else:
        lshape = tuple(d[:-1]) if len(d) > 1 else (d[0],)
    return [d, lshape], [tuple(d)], []


@register("SoftmaxOutput", inputs=("data", "label"),
          params={"grad_scale": Param(float, 1.0), "ignore_label": Param(float, -1.0),
                  "multi_output": Param(bool, False), "use_ignore": Param(bool, False),
                  "preserve_shape": Param(bool, False),
                  "normalization": Param(str, "null", enum=("null", "batch", "valid")),
                  "out_grad": Param(bool, False)},
          infer_shape=_softmax_label_infer, no_grad_inputs=("label",),
          aliases=("Softmax",), hint="softmaxoutput")
def _softmax_output(opctx, attrs, data, label):
    return _softmax_output_impl(
        data, label, attrs.get("grad_scale", 1.0), attrs.get("ignore_label", -1.0),
        bool(attrs.get("multi_output", False)), bool(attrs.get("use_ignore", False)),
        attrs.get("normalization", "null"))


@register("SoftmaxActivation",
          params={"mode": Param(str, "instance", enum=("instance", "channel"))},
          hint="softmaxactivation")
def _softmax_activation(opctx, attrs, x):
    if attrs.get("mode", "instance") == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


def _make_regression(name, fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def impl(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label)

    def bwd(grad_scale, res, ct):
        del ct  # regression_output-inl.h:56-80 — head grad ignored
        out, label = res
        num_output = int(np.prod(label.shape[1:])) if label.ndim > 1 else 1
        g = bwd_fn(out, label.reshape(out.shape)) * (grad_scale / num_output)
        return g.astype(out.dtype), jnp.zeros_like(label)

    impl.defvjp(fwd, bwd)

    def label_infer(attrs, in_shapes):
        d = in_shapes[0]
        if d is None:
            return in_shapes, [None], []
        if len(d) == 2 and d[1] == 1:
            lshape = (d[0],)
        else:
            lshape = tuple(d)
        return [d, lshape], [tuple(d)], []

    @register(name, inputs=("data", "label"),
              params={"grad_scale": Param(float, 1.0)},
              infer_shape=label_infer, no_grad_inputs=("label",),
              hint=name.lower())
    def _op(opctx, attrs, data, label):
        return impl(data, label, attrs.get("grad_scale", 1.0))


_make_regression("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)
_make_regression("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output_impl(data, label, margin, coef, use_linear):
    return data


def _svm_fwd(data, label, margin, coef, use_linear):
    return data, (data, label)


def _svm_bwd(margin, coef, use_linear, res, ct):
    del ct
    data, label = res
    n, c = data.shape[0], data.shape[-1]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), c, dtype=data.dtype)
    sign = 1 - 2 * onehot  # -1 at the true class, +1 elsewhere
    dist = margin - data * (2 * onehot - 1)
    viol = (dist > 0).astype(data.dtype)
    if use_linear:
        grad = coef * sign * viol
    else:
        grad = 2 * coef * sign * viol * dist
    return grad.astype(data.dtype), jnp.zeros_like(label)


_svm_output_impl.defvjp(_svm_fwd, _svm_bwd)


@register("SVMOutput", inputs=("data", "label"),
          params={"margin": Param(float, 1.0),
                  "regularization_coefficient": Param(float, 1.0),
                  "use_linear": Param(bool, False)},
          infer_shape=_softmax_label_infer, no_grad_inputs=("label",),
          hint="svmoutput")
def _svm_output(opctx, attrs, data, label):
    return _svm_output_impl(data, label, attrs.get("margin", 1.0),
                            attrs.get("regularization_coefficient", 1.0),
                            bool(attrs.get("use_linear", False)))


@register("MakeLoss",
          params={"grad_scale": Param(float, 1.0), "valid_thresh": Param(float, 0.0),
                  "normalization": Param(str, "null", enum=("null", "batch", "valid"))},
          hint="makeloss")
def _make_loss_layer(opctx, attrs, x):
    """Legacy MakeLoss layer (src/operator/make_loss-inl.h): identity forward,
    constant grad_scale backward with batch/valid normalization."""
    gs = attrs.get("grad_scale", 1.0)
    norm = attrs.get("normalization", "null")
    thresh = attrs.get("valid_thresh", 0.0)

    @jax.custom_vjp
    def impl(x):
        return x

    def fwd(x):
        return x, x

    def bwd(res, ct):
        del ct
        x = res
        if norm == "batch":
            scale = gs / x.shape[0]
            return (jnp.full(x.shape, scale, x.dtype),)
        if norm == "valid":
            valid = jnp.maximum(jnp.sum((x > thresh).astype(x.dtype)), 1.0)
            return (jnp.full(x.shape, gs, x.dtype) / valid,)
        return (jnp.full(x.shape, gs, x.dtype),)

    impl.defvjp(fwd, bwd)
    return impl(x)


@register("softmax_cross_entropy", inputs=("data", "label"),
          no_grad_inputs=("label",),
          infer_shape=lambda attrs, s: (s, [(1,)], []))
def _softmax_cross_entropy(opctx, attrs, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=data.dtype)
    return -jnp.sum(onehot * logp).reshape((1,))


@register("IdentityAttachKLSparseReg",
          aux=("moving_avg",),
          params={"sparseness_target": Param(float, 0.1),
                  "penalty": Param(float, 0.001), "momentum": Param(float, 0.9)},
          infer_shape=lambda attrs, s: (
              s, [s[0]], [(s[0][1],) if s[0] is not None else None]),
          hint="identityattachklsparsereg")
def _identity_kl_sparse(opctx, attrs, data, moving_avg):
    """Identity with KL-sparsity gradient penalty on the (sigmoid) activations
    (reference: src/operator/identity_attach_KL_sparse_reg-inl.h)."""
    st = attrs.get("sparseness_target", 0.1)
    pen = attrs.get("penalty", 0.001)
    mom = attrs.get("momentum", 0.9)
    rho = jnp.mean(data, axis=tuple(i for i in range(data.ndim) if i != 1))
    new_avg = mom * moving_avg + (1 - mom) * lax.stop_gradient(rho)

    @jax.custom_vjp
    def impl(x, rho_hat):
        return x

    def fwd(x, rho_hat):
        return x, (x.shape, x.dtype, rho_hat)

    def bwd(res, ct):
        shape, dtype, rho_hat = res
        kl_grad = pen * (-st / (rho_hat + 1e-12) + (1 - st) / (1 - rho_hat + 1e-12))
        bshape = (1, -1) + (1,) * (len(shape) - 2)
        return (ct + kl_grad.reshape(bshape).astype(dtype), jnp.zeros_like(rho_hat))

    impl.defvjp(fwd, bwd)
    return impl(data, lax.stop_gradient(rho)), new_avg


# ---------------------------------------------------------------------------
# UpSampling
# ---------------------------------------------------------------------------


def _upsampling_inputs(attrs):
    n = int(attrs.get("num_args", 1))
    if attrs.get("sample_type") == "bilinear":
        return ["data", "weight"]
    return ["arg%d" % i for i in range(n)] if n > 1 else ["data"]


def _upsampling_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    s = attrs["scale"]
    out = (d[0], sum(x[1] for x in in_shapes if x is not None) if len(in_shapes) > 1
           and attrs.get("sample_type") != "bilinear" else d[1], d[2] * s, d[3] * s)
    if attrs.get("sample_type") == "bilinear":
        k = 2 * s - s % 2
        return [d, (d[1], 1, k, k)], [out], []
    return in_shapes, [out], []


@register("UpSampling", inputs=_upsampling_inputs, key_var_num_args="num_args",
          params={"scale": Param(int, required=True), "num_filter": Param(int, 0),
                  "sample_type": Param(str, required=True, enum=("nearest", "bilinear")),
                  "multi_input_mode": Param(str, "concat", enum=("concat", "sum")),
                  "num_args": Param(int, 1), "workspace": Param(int, 512)},
          infer_shape=_upsampling_infer, hint="upsampling")
def _upsampling(opctx, attrs, *args):
    s = attrs["scale"]
    stype = attrs["sample_type"]
    if stype == "nearest":
        outs = []
        for x in args:
            up = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
            outs.append(up)
        if len(outs) == 1:
            return outs[0]
        if attrs.get("multi_input_mode", "concat") == "sum":
            out = outs[0]
            for o in outs[1:]:
                out = out + o
            return out
        return jnp.concatenate(outs, axis=1)
    # bilinear: grouped deconvolution with the provided weight
    data, weight = args
    c = data.shape[1]
    k = 2 * s - s % 2
    pad = (s - 1) // 2 if s % 2 else s // 2  # int(ceil((s-1)/2)) symmetric-ish
    dn = _conv_dnums(2)
    w = jnp.flip(weight, axis=(2, 3))
    out = lax.conv_general_dilated(
        data, w, window_strides=(1, 1),
        padding=[(k - 1 - pad, k - 1 - pad)] * 2,
        lhs_dilation=(s, s), dimension_numbers=dn, feature_group_count=c)
    return out

"""Operator registry — single source of truth for the op surface.

TPU-native redesign of the reference's three-generation op machinery
(``MXNET_REGISTER_OP_PROPERTY`` legacy layers, ``NNVM_REGISTER_OP`` FCompute
tensor ops, and ``MXNET_REGISTER_SIMPLE_OP`` — see
include/mxnet/operator.h:77-480 and include/mxnet/op_attr_types.h:33-63 in
/root/reference).  Here there is ONE registration form: a pure function over
``jax.numpy`` arrays plus declarative metadata.  The registry drives

* the auto-generated imperative API (``mx.nd.<op>``) — analogue of the
  reference's import-time codegen from the C op registry
  (python/mxnet/_ctypes/ndarray.py:165-200),
* the symbolic API (``mx.sym.<op>``) and graph JSON round-trip,
* shape/type inference (per-op ``infer_shape`` for ops that can deduce
  parameter shapes; jax.eval_shape as the fallback oracle),
* autodiff: gradients come from JAX tracing through ``fn`` — custom
  gradients (loss ops, stop-gradient semantics) are expressed with
  ``jax.custom_vjp`` inside ``fn`` instead of hand-written ``_backward_*``
  ops.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Op", "OpContext", "register", "get_op", "list_ops", "registered_ops"]


class OpContext:
    """Per-invocation context handed to op kernels (reference: OpContext in
    include/mxnet/operator.h:60-75 — is_train + requested resources).  The
    RNG resource (reference: ResourceManager ResourceRandom, src/resource.cc:144)
    is a JAX PRNG key, split per stochastic op by the caller."""

    __slots__ = ("is_train", "rng")

    def __init__(self, is_train: bool = False, rng=None):
        self.is_train = is_train
        self.rng = rng


class Op:
    def __init__(
        self,
        name: str,
        fn: Callable,
        inputs: Any = ("data",),
        params: Optional[Dict[str, Any]] = None,
        num_outputs: Any = 1,
        aux: Sequence[str] = (),
        stochastic: bool = False,
        key_var_num_args: Optional[str] = None,
        infer_shape: Optional[Callable] = None,
        infer_type: Optional[Callable] = None,
        output_names: Optional[Callable] = None,
        hint: Optional[str] = None,
        no_grad_inputs: Sequence[str] = (),
        aux_dtype: Optional[str] = None,
        allow_extra_attrs: bool = False,
        doc: str = "",
    ):
        self.name = name
        self.fn = fn
        self._inputs = inputs
        self.params = params or {}
        self._num_outputs = num_outputs
        # aux may be a callable(attrs) -> names for ops whose auxiliary-state
        # list depends on attrs (the Custom op: CustomOpProp.list_auxiliary_states)
        self.aux = aux if callable(aux) else tuple(aux)
        self.allow_extra_attrs = allow_extra_attrs
        self.stochastic = stochastic
        self.key_var_num_args = key_var_num_args
        self.infer_shape = infer_shape
        self.infer_type = infer_type
        self._output_names = output_names
        self.hint = hint or name.lower().lstrip("_")
        self.no_grad_inputs = tuple(no_grad_inputs)
        # aux states' dtype: None = follow the op's first input dtype;
        # "float32" pins it (BatchNorm moving stats, reference semantics).
        self.aux_dtype = aux_dtype
        self.doc = doc

    # -- metadata ----------------------------------------------------------
    def input_names(self, attrs: Dict[str, Any]) -> List[str]:
        if callable(self._inputs):
            return list(self._inputs(attrs))
        if self.key_var_num_args and self.key_var_num_args in attrs:
            n = int(attrs[self.key_var_num_args])
            return ["arg%d" % i for i in range(n)]
        return list(self._inputs)

    def num_outputs(self, attrs: Dict[str, Any]) -> int:
        if callable(self._num_outputs):
            return int(self._num_outputs(attrs))
        return int(self._num_outputs)

    def output_names(self, attrs: Dict[str, Any], node_name: str) -> List[str]:
        if self._output_names is not None:
            names = self._output_names(attrs)
            return ["%s_%s" % (node_name, n) for n in names]
        n = self.num_outputs(attrs)
        if n == 1:
            return ["%s_output" % node_name]
        return ["%s_output%d" % (node_name, i) for i in range(n)]

    def aux_names(self, attrs: Dict[str, Any]) -> List[str]:
        if callable(self.aux):
            return list(self.aux(attrs))
        return list(self.aux)

    def parse_attrs(self, attrs: Dict[str, Any]) -> Dict[str, Any]:
        from .param import parse_attrs

        return parse_attrs(self.params, attrs, self.name,
                           allow_extra=self.allow_extra_attrs)

    # -- application -------------------------------------------------------
    def apply(self, opctx: OpContext, attrs: Dict[str, Any], inputs, aux=()):
        """Run the kernel.  Returns (outputs: tuple, aux_updates: tuple)."""
        result = self.fn(opctx, attrs, *inputs, *aux)
        if not isinstance(result, tuple):
            result = (result,)
        n_out = self.num_outputs(attrs)
        n_aux = len(aux)
        if n_aux and len(result) == n_out + n_aux:
            return result[:n_out], result[n_out:]
        return result, tuple(aux)

    def __repr__(self):
        return "Op(%s)" % self.name


_REGISTRY: Dict[str, Op] = {}


def register(name: str, **kwargs) -> Callable:
    """Decorator registering an op kernel.  ``aliases`` registers extra names
    pointing at the same Op (reference keeps e.g. both ``Flatten`` and
    ``flatten``)."""
    aliases = kwargs.pop("aliases", ())

    def deco(fn: Callable) -> Callable:
        op = Op(name, fn, doc=fn.__doc__ or "", **kwargs)
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return fn

    return deco


def get_op(name: str) -> Op:
    if name not in _REGISTRY:
        raise KeyError("Operator %s is not registered" % name)
    return _REGISTRY[name]


def list_ops() -> List[str]:
    return sorted(_REGISTRY)


def registered_ops() -> Dict[str, Op]:
    return _REGISTRY

"""Spatial/vision layer ops: GridGenerator, BilinearSampler,
SpatialTransformer, ROIPooling, Correlation.

Parity surface: /root/reference/src/operator/{grid_generator,
bilinear_sampler, spatial_transformer, roi_pooling, correlation}-inl.h.
All implemented as dense, statically-shaped jnp computations (gathers +
masked reductions) so XLA can tile them — no dynamic shapes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .param import Param
from .registry import register


# ---------------------------------------------------------------------------
# GridGenerator / BilinearSampler / SpatialTransformer
# ---------------------------------------------------------------------------


def _affine_grid(theta, target_shape):
    """theta (N, 6) -> sampling grid (N, 2, H, W) in [-1, 1] (x, y order,
    matching grid_generator-inl.h)."""
    h, w = target_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, H*W)
    mat = theta.reshape(-1, 2, 3)
    out = jnp.einsum("nij,jk->nik", mat, coords)  # (N, 2, H*W)
    return out.reshape(theta.shape[0], 2, h, w)


def _grid_gen_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    if attrs.get("transform_type", "affine") == "affine":
        h, w = attrs["target_shape"]
        return in_shapes, [(d[0], 2, h, w)], []
    return in_shapes, [tuple(d)], []


@register("GridGenerator",
          params={"transform_type": Param(str, "affine", enum=("affine", "warp")),
                  "target_shape": Param("shape", (0, 0))},
          infer_shape=_grid_gen_infer, hint="gridgenerator")
def _grid_generator(opctx, attrs, data):
    if attrs.get("transform_type", "affine") == "affine":
        return _affine_grid(data, attrs["target_shape"])
    # warp: data is a flow field (N, 2, H, W) in pixels; output normalized grid
    n, _, h, w = data.shape
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    x = (data[:, 0] + gx) / max((w - 1) / 2.0, 1e-12) - 1.0
    y = (data[:, 1] + gy) / max((h - 1) / 2.0, 1e-12) - 1.0
    return jnp.stack([x, y], axis=1)


def _bilinear_sample(data, grid):
    """Sample data (N,C,H,W) at grid (N,2,Ho,Wo) in [-1,1]; zero padding
    outside (bilinear_sampler-inl.h semantics)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0  # (N, Ho, Wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        # (N, C, Ho, Wo) gather per batch
        batch = jnp.arange(n).reshape(n, 1, 1)
        vals = data[batch, :, yc, xc]  # (N, Ho, Wo, C)
        vals = jnp.moveaxis(vals, -1, 1)
        return vals * valid[:, None, :, :].astype(data.dtype)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    return (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
            + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)


def _bilinear_infer(attrs, in_shapes):
    d, g = in_shapes
    if d is None or g is None:
        return in_shapes, [None], []
    return in_shapes, [(d[0], d[1], g[2], g[3])], []


@register("BilinearSampler", inputs=("data", "grid"), infer_shape=_bilinear_infer,
          hint="bilinearsampler")
def _bilinear_sampler(opctx, attrs, data, grid):
    return _bilinear_sample(data, grid)


def _st_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    th, tw = attrs.get("target_shape", (0, 0))
    h = th or d[2]
    w = tw or d[3]
    return [d, (d[0], 6)], [(d[0], d[1], h, w)], []


@register("SpatialTransformer", inputs=("data", "loc"),
          params={"target_shape": Param("shape", (0, 0)),
                  "transform_type": Param(str, "affine", enum=("affine",)),
                  "sampler_type": Param(str, "bilinear", enum=("bilinear",))},
          infer_shape=_st_infer, hint="spatialtransformer")
def _spatial_transformer(opctx, attrs, data, loc):
    th, tw = attrs.get("target_shape", (0, 0))
    h = th or data.shape[2]
    w = tw or data.shape[3]
    grid = _affine_grid(loc, (h, w))
    return _bilinear_sample(data, grid)


# ---------------------------------------------------------------------------
# ROIPooling
# ---------------------------------------------------------------------------


def _roi_infer(attrs, in_shapes):
    d, r = in_shapes
    if d is None or r is None:
        return in_shapes, [None], []
    ph, pw = attrs["pooled_size"]
    return in_shapes, [(r[0], d[1], ph, pw)], []


@register("ROIPooling", inputs=("data", "rois"),
          params={"pooled_size": Param("shape", required=True),
                  "spatial_scale": Param(float, required=True)},
          infer_shape=_roi_infer, no_grad_inputs=("rois",), hint="roipooling")
def _roi_pooling(opctx, attrs, data, rois):
    """Max-pool each ROI into a fixed (ph, pw) grid (roi_pooling-inl.h).
    Static bin loop + masked max keeps shapes static for XLA."""
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    n, c, h, w = data.shape

    batch_idx = rois[:, 0].astype(jnp.int32)  # (R,)
    x0 = jnp.round(rois[:, 1] * scale)
    y0 = jnp.round(rois[:, 2] * scale)
    x1 = jnp.round(rois[:, 3] * scale)
    y1 = jnp.round(rois[:, 4] * scale)
    roi_h = jnp.maximum(y1 - y0 + 1.0, 1.0)
    roi_w = jnp.maximum(x1 - x0 + 1.0, 1.0)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    feat = data[batch_idx]  # (R, C, H, W)
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)

    neg = jnp.asarray(-np.inf, data.dtype)
    rows = []
    for py in range(ph):
        hstart = jnp.floor(y0 + py * bin_h)
        hend = jnp.ceil(y0 + (py + 1) * bin_h)
        ymask = (ys[None, :] >= hstart[:, None]) & (ys[None, :] < hend[:, None])
        cols = []
        for px in range(pw):
            wstart = jnp.floor(x0 + px * bin_w)
            wend = jnp.ceil(x0 + (px + 1) * bin_w)
            xmask = (xs[None, :] >= wstart[:, None]) & (xs[None, :] < wend[:, None])
            mask = ymask[:, None, :, None] & xmask[:, None, None, :]  # (R,1,H,W)
            vals = jnp.where(mask, feat, neg)
            pooled = jnp.max(vals, axis=(2, 3))  # (R, C)
            pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
            cols.append(pooled)
        rows.append(jnp.stack(cols, axis=-1))  # (R, C, PW)
    return jnp.stack(rows, axis=-2)  # (R, C, PH, PW)


# ---------------------------------------------------------------------------
# Correlation (FlowNet-style)
# ---------------------------------------------------------------------------


def _corr_infer(attrs, in_shapes):
    d1 = in_shapes[0]
    if d1 is None:
        return in_shapes, [None], []
    pad = attrs.get("pad_size", 0)
    k = attrs.get("kernel_size", 1)
    md = attrs.get("max_displacement", 1)
    s1 = attrs.get("stride1", 1)
    s2 = attrs.get("stride2", 1)
    ph, pw = d1[2] + 2 * pad, d1[3] + 2 * pad
    bd = md // s2
    neigh = (2 * bd + 1) ** 2
    kr = k // 2
    border = md + kr
    oh = int(np.ceil((ph - border * 2) / s1))
    ow = int(np.ceil((pw - border * 2) / s1))
    return in_shapes, [(d1[0], neigh, oh, ow)], []


@register("Correlation", inputs=("data1", "data2"),
          params={"kernel_size": Param(int, 1), "max_displacement": Param(int, 1),
                  "stride1": Param(int, 1), "stride2": Param(int, 1),
                  "pad_size": Param(int, 0), "is_multiply": Param(bool, True)},
          infer_shape=_corr_infer, hint="correlation")
def _correlation(opctx, attrs, data1, data2):
    pad = attrs.get("pad_size", 0)
    k = attrs.get("kernel_size", 1)
    md = attrs.get("max_displacement", 1)
    s1 = attrs.get("stride1", 1)
    s2 = attrs.get("stride2", 1)
    mult = attrs.get("is_multiply", True)
    n, c, _, _ = data1.shape
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = p1.shape[2], p1.shape[3]
    kr = k // 2
    border = md + kr
    oh = int(np.ceil((ph - border * 2) / s1))
    ow = int(np.ceil((pw - border * 2) / s1))
    bd = md // s2
    ys = border + jnp.arange(oh) * s1
    xs = border + jnp.arange(ow) * s1
    out_maps = []
    ksz = float(k * k * c)
    for dy in range(-bd, bd + 1):
        for dx in range(-bd, bd + 1):
            acc = 0.0
            for ky in range(-kr, kr + 1):
                for kx in range(-kr, kr + 1):
                    a = p1[:, :, ys[:, None] + ky, xs[None, :] + kx]
                    b = p2[:, :, ys[:, None] + ky + dy * s2, xs[None, :] + kx + dx * s2]
                    if mult:
                        acc = acc + jnp.sum(a * b, axis=1)
                    else:
                        acc = acc + jnp.sum(jnp.abs(a - b), axis=1)
            out_maps.append(acc / ksz)
    return jnp.stack(out_maps, axis=1)

"""Ordering ops: sort / argsort / topk.

Parity surface: /root/reference/src/operator/tensor/ordering_op-inl.h.
``topk`` keeps the reference's ret_typ variants (value/indices/mask/both) and
float index outputs.  lax.top_k / XLA sort replace the reference's
per-row mergesort kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .param import Param
from .registry import register

_SORT_SPEC = {"axis": Param("int-or-none", -1), "is_ascend": Param(bool, True)}


@register("sort", params=dict(_SORT_SPEC))
def _sort(opctx, attrs, x):
    axis = attrs.get("axis", -1)
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    out = jnp.sort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", params=dict(_SORT_SPEC), no_grad_inputs=("data",))
def _argsort(opctx, attrs, x):
    axis = attrs.get("axis", -1)
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    idx = jnp.argsort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(x.dtype)


_TOPK_SPEC = {
    "axis": Param("int-or-none", -1),
    "k": Param(int, 1),
    "ret_typ": Param(str, "indices", enum=("value", "indices", "mask", "both")),
    "is_ascend": Param(bool, False),
}


def _topk_outputs(attrs):
    return 2 if attrs.get("ret_typ", "indices") == "both" else 1


def _topk_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    n = _topk_outputs(attrs)
    if ishape is None:
        return in_shapes, [None] * n, []
    axis = attrs.get("axis", -1)
    k = attrs.get("k", 1)
    ret = attrs.get("ret_typ", "indices")
    if ret == "mask":
        return in_shapes, [tuple(ishape)], []
    if axis is None:
        out = (k,)
    else:
        out = list(ishape)
        out[axis % len(ishape)] = k
        out = tuple(out)
    return in_shapes, [out] * n, []


@register("topk", params=dict(_TOPK_SPEC), num_outputs=_topk_outputs,
          infer_shape=_topk_infer, no_grad_inputs=("data",))
def _topk(opctx, attrs, x):
    axis = attrs.get("axis", -1)
    k = int(attrs.get("k", 1))
    asc = attrs.get("is_ascend", False)
    ret = attrs.get("ret_typ", "indices")
    orig_shape = x.shape
    if axis is None:
        xm = x.reshape(1, -1)
        axis_ = 1
    else:
        axis_ = axis % x.ndim
        xm = jnp.moveaxis(x, axis_, -1)
    vals, idx = jax.lax.top_k(-xm if asc else xm, k)
    if asc:
        vals = -vals
    if ret == "mask":
        mask = jnp.zeros_like(xm).at[
            tuple(jnp.indices(idx.shape)[:-1]) + (idx,)
        ].set(1.0)
        if axis is None:
            return mask.reshape(orig_shape)
        return jnp.moveaxis(mask, -1, axis_)
    if axis is None:
        vals, idx = vals.reshape(-1), idx.reshape(-1)
    else:
        vals = jnp.moveaxis(vals, -1, axis_)
        idx = jnp.moveaxis(idx, -1, axis_)
    fidx = idx.astype(x.dtype)
    if ret == "value":
        return vals
    if ret == "indices":
        return fidx
    return vals, fidx

"""Paged-KV attention ops — the decode-step kernels behind
``mxnet_tpu.generation`` (continuous batching + paged KV-cache).

Two ops:

* ``_contrib_DenseAttention`` — plain dense softmax attention over
  ``[b, s, h, d]`` (the ``parallel.ring.local_attention`` oracle as a
  symbol op).  The generation prefill path uses it instead of the Pallas
  flash kernels because interpret-mode Pallas is orders of magnitude too
  slow on CPU, and prefill happens once per sequence; on TPU the flash
  kernels remain the training/high-MFU choice (models/transformer.py).

* ``_contrib_PagedAttention`` — one autoregressive decode step over a
  paged KV pool (the vLLM PagedAttention layout): each decode *lane*
  holds one live sequence whose K/V history lives in fixed-size pages of
  a shared pool, indirected through a per-lane page table.  The op
  WRITES the lane's new K/V at ``positions[lane]`` into the pool, then
  attends the lane's query against its own gathered history.  Because
  pools, page tables, and lane vectors are all fixed-shape, the whole
  decode step is ONE static XLA program per lane-count bucket — no
  per-sequence-length recompiles, which is the entire point
  (ISSUE 12 / Operator Fusion in XLA, arxiv 2301.13062).

Page 0 of the pool is reserved as a scratch page: inactive lanes carry
an all-zero page-table row and position 0, so their (masked-out) writes
land harmlessly in the scratch page and never corrupt a live sequence.
"""
from __future__ import annotations

import numpy as np

from .param import Param
from .registry import register

_NEG = -1e30


def _dense_infer(attrs, shapes):
    return shapes, [shapes[0]], []


@register("_contrib_DenseAttention",
          inputs=("query", "key", "value"),
          params={"causal": Param(bool, True),
                  "scale": Param("float-or-none", None)},
          infer_shape=_dense_infer, hint="denseattention")
def _dense_attention(opctx, attrs, query, key, value):
    from ..parallel.ring import local_attention

    scale = attrs.get("scale")
    return local_attention(query, key, value,
                           causal=bool(attrs.get("causal", True)),
                           scale=None if scale is None else float(scale))


def _paged_infer(attrs, shapes):
    q, k_new, v_new, k_pool, v_pool, page_table, positions = shapes
    if q is None or k_pool is None:
        return shapes, [None, None, None], []
    return shapes, [q, k_pool, v_pool], []


@register("_contrib_PagedAttentionWindow",
          inputs=("query", "key", "value", "k_pool", "v_pool",
                  "page_table", "positions"),
          params={"page_size": Param(int, required=True),
                  "scale": Param("float-or-none", None)},
          num_outputs=3, infer_shape=_paged_infer,
          no_grad_inputs=("page_table", "positions"),
          output_names=lambda attrs: ["out", "k_pool_out", "v_pool_out"],
          hint="pagedattentionwindow")
def _paged_attention_window(opctx, attrs, q, k_new, v_new, k_pool, v_pool,
                            page_table, positions):
    """``width`` KNOWN tokens per lane in ONE causal pass over paged KV.

    The sequential decode chain is only necessary when each token must
    be *discovered* from the previous logits.  When the whole window is
    known up front — a prefix-cache catch-up walking a prompt suffix, a
    re-admitted preemptee re-materializing its transcript — teacher
    forcing applies: write all ``width`` new K/V slots, gather each
    lane's history ONCE, and attend all ``width`` queries under a
    per-query causal mask.  Same numerics family as the chained
    construction at a fraction of the gathers (2 per layer instead of
    2 per layer per token) and with every projection batched over
    ``lanes * width`` rows instead of ``lanes``.

    Shapes (all static):
      q, k_new, v_new : (lanes * width, heads, head_dim)
      k_pool, v_pool  : (num_pages, page_size, heads, head_dim)
      page_table      : (lanes, max_pages)
      positions       : (lanes, width) absolute position per window slot
                        (pad slots point at the scratch page, as decode)
    Returns (att_out (lanes * width, heads, head_dim), k_pool_out,
    v_pool_out).
    """
    import jax.numpy as jnp

    ps = int(attrs["page_size"])
    lanes, width = positions.shape
    heads, hd = q.shape[-2], q.shape[-1]
    num_pages = k_pool.shape[0]
    max_pages = page_table.shape[1]
    scale = attrs.get("scale")
    scale = (1.0 / np.sqrt(hd)) if scale is None else float(scale)

    pt = page_table.astype(jnp.int32)
    pos = positions.astype(jnp.int32)  # (lanes, width)

    # -- write: the whole window's K/V into each lane's slots ------------
    flat_k = k_pool.reshape(num_pages * ps, heads, hd)
    flat_v = v_pool.reshape(num_pages * ps, heads, hd)
    page_idx = jnp.take_along_axis(pt, pos // ps, axis=1)  # (lanes, width)
    slot = (page_idx * ps + pos % ps).reshape(-1)
    flat_k = flat_k.at[slot].set(k_new.astype(flat_k.dtype))
    flat_v = flat_v.at[slot].set(v_new.astype(flat_v.dtype))

    # -- gather ONCE: each lane's full history, in token order -----------
    ctx_idx = (pt[:, :, None] * ps
               + jnp.arange(ps, dtype=jnp.int32)[None, None, :])
    ctx_idx = ctx_idx.reshape(lanes, max_pages * ps)
    keys = flat_k[ctx_idx]    # (lanes, T, heads, hd)
    vals = flat_v[ctx_idx]

    # -- causal masked attention, all width queries at once --------------
    qw = q.reshape(lanes, width, heads, hd)
    s = jnp.einsum("lwhd,lthd->lwht", qw, keys).astype(jnp.float32) * scale
    valid = (jnp.arange(max_pages * ps, dtype=jnp.int32)[None, None, :]
             <= pos[:, :, None])  # (lanes, width, T)
    s = jnp.where(valid[:, :, None, :], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    out = jnp.einsum("lwht,lthd->lwhd", p, vals).astype(q.dtype)
    return (out.reshape(lanes * width, heads, hd),
            flat_k.reshape(num_pages, ps, heads, hd),
            flat_v.reshape(num_pages, ps, heads, hd))


@register("_contrib_PagedAttention",
          inputs=("query", "key", "value", "k_pool", "v_pool",
                  "page_table", "positions"),
          params={"page_size": Param(int, required=True),
                  "scale": Param("float-or-none", None)},
          num_outputs=3, infer_shape=_paged_infer,
          no_grad_inputs=("page_table", "positions"),
          output_names=lambda attrs: ["out", "k_pool_out", "v_pool_out"],
          hint="pagedattention")
def _paged_attention(opctx, attrs, q, k_new, v_new, k_pool, v_pool,
                     page_table, positions):
    """One decode step for ``lanes`` sequences at once.

    Shapes (all static):
      q, k_new, v_new : (lanes, heads, head_dim) — this step's projections
      k_pool, v_pool  : (num_pages, page_size, heads, head_dim)
      page_table      : (lanes, max_pages) pool-page ids per lane, in
                        sequence order (float carrier, cast to int32 —
                        Predictor feeds every input as its bind dtype)
      positions       : (lanes,) this token's absolute position per lane
    Returns (att_out, k_pool_out, v_pool_out).
    """
    import jax.numpy as jnp

    ps = int(attrs["page_size"])
    lanes, heads, hd = q.shape
    num_pages = k_pool.shape[0]
    max_pages = page_table.shape[1]
    scale = attrs.get("scale")
    scale = (1.0 / np.sqrt(hd)) if scale is None else float(scale)

    pt = page_table.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    # -- write: this step's K/V into each lane's current slot ------------
    flat_k = k_pool.reshape(num_pages * ps, heads, hd)
    flat_v = v_pool.reshape(num_pages * ps, heads, hd)
    cur_page = jnp.take_along_axis(pt, (pos // ps)[:, None], axis=1)[:, 0]
    slot = cur_page * ps + pos % ps  # (lanes,) — inactive lanes hit page 0
    flat_k = flat_k.at[slot].set(k_new.astype(flat_k.dtype))
    flat_v = flat_v.at[slot].set(v_new.astype(flat_v.dtype))

    # -- gather: each lane's full history, in token order ----------------
    # token t of a lane lives at page_table[lane, t // ps], offset t % ps,
    # so gathering the lane's pages in table order yields exactly tokens
    # 0..max_pages*ps-1 at their flattened indices.
    ctx_idx = (pt[:, :, None] * ps
               + jnp.arange(ps, dtype=jnp.int32)[None, None, :])
    ctx_idx = ctx_idx.reshape(lanes, max_pages * ps)
    keys = flat_k[ctx_idx]    # (lanes, T, heads, hd)
    vals = flat_v[ctx_idx]

    # -- masked softmax attention (local_attention numerics) -------------
    s = jnp.einsum("lhd,lthd->lht", q, keys).astype(jnp.float32) * scale
    valid = (jnp.arange(max_pages * ps, dtype=jnp.int32)[None, :]
             <= pos[:, None])  # causal: history up to and incl. this token
    s = jnp.where(valid[:, None, :], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    out = jnp.einsum("lht,lthd->lhd", p, vals).astype(q.dtype)
    return (out,
            flat_k.reshape(num_pages, ps, heads, hd),
            flat_v.reshape(num_pages, ps, heads, hd))

"""Initialization ops (_zeros/_ones/_full/_arange, *_like).

Parity surface: /root/reference/src/operator/tensor/init_op.{h,cc}.
"""
from __future__ import annotations

import jax.numpy as jnp

from .param import Param, _np_dtype
from .registry import register

_INIT_SPEC = {
    "shape": Param("shape", ()),
    "dtype": Param("dtype", "float32"),
    "ctx": Param(str, ""),
}


def _init_infer(attrs, in_shapes):
    return in_shapes, [tuple(attrs.get("shape") or ())], []


@register("_zeros", inputs=(), params=dict(_INIT_SPEC), infer_shape=_init_infer,
          hint="zeros")
def _zeros(opctx, attrs):
    return jnp.zeros(attrs.get("shape") or (), _np_dtype(attrs.get("dtype", "float32")))


@register("_ones", inputs=(), params=dict(_INIT_SPEC), infer_shape=_init_infer,
          hint="ones")
def _ones(opctx, attrs):
    return jnp.ones(attrs.get("shape") or (), _np_dtype(attrs.get("dtype", "float32")))


@register("_full", inputs=(), params={**_INIT_SPEC, "value": Param(float, 0.0)},
          infer_shape=_init_infer, hint="full")
def _full(opctx, attrs):
    return jnp.full(attrs.get("shape") or (), attrs.get("value", 0.0),
                    _np_dtype(attrs.get("dtype", "float32")))


def _arange_vals(attrs):
    import numpy as np

    start = attrs.get("start", 0.0)
    stop = attrs.get("stop")
    step = attrs.get("step", 1.0)
    rep = int(attrs.get("repeat", 1))
    if stop is None:
        start, stop = 0.0, start
    vals = np.arange(start, stop, step)
    if rep > 1:
        vals = np.repeat(vals, rep)
    return vals


@register("_arange", inputs=(),
          params={"start": Param(float, 0.0), "stop": Param("float-or-none", None),
                  "step": Param(float, 1.0), "repeat": Param(int, 1),
                  "dtype": Param("dtype", "float32"), "ctx": Param(str, "")},
          infer_shape=lambda attrs, s: (s, [(len(_arange_vals(attrs)),)], []),
          hint="arange")
def _arange(opctx, attrs):
    return jnp.asarray(_arange_vals(attrs), _np_dtype(attrs.get("dtype", "float32")))


@register("zeros_like")
def _zeros_like(opctx, attrs, x):
    return jnp.zeros_like(x)


@register("ones_like")
def _ones_like(opctx, attrs, x):
    return jnp.ones_like(x)


@register("_set_value", inputs=(), params={"src": Param(float, 0.0)})
def _set_value(opctx, attrs, *a):
    """Imperative fill; the ndarray layer routes out= handling
    (reference: ndarray.cc _set_value NDArray function)."""
    return jnp.asarray(attrs.get("src", 0.0))

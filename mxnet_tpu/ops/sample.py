"""Sampling ops (uniform / normal / gamma / exponential / poisson /
negative_binomial / generalized_negative_binomial).

Parity surface: /root/reference/src/operator/tensor/sample_op.{h,cc} —
``_sample_uniform``/``_sample_normal`` (exposed as mx.random.uniform/normal
and mx.nd.uniform/normal).  TPU-native: per-call JAX PRNG keys split from the
seeded stream (analogue of ResourceRandom, src/resource.cc:144) instead of
per-device cuRAND generators.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .param import Param, _np_dtype
from .registry import register

_SAMPLE_SPEC = {
    "shape": Param("shape", ()),
    "dtype": Param("dtype", "float32"),
    "ctx": Param(str, ""),
}


def _sample_infer(attrs, in_shapes):
    return in_shapes, [tuple(attrs.get("shape") or ())], []


def _shape_dtype(attrs):
    return tuple(attrs.get("shape") or ()), _np_dtype(attrs.get("dtype", "float32"))


@register("_sample_uniform", inputs=(),
          params={**_SAMPLE_SPEC, "low": Param(float, 0.0), "high": Param(float, 1.0)},
          stochastic=True, infer_shape=_sample_infer,
          aliases=("uniform", "random_uniform"), hint="uniform")
def _sample_uniform(opctx, attrs, *a):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.uniform(opctx.rng, shape, dtype,
                              minval=attrs.get("low", 0.0), maxval=attrs.get("high", 1.0))


@register("_sample_normal", inputs=(),
          params={**_SAMPLE_SPEC, "loc": Param(float, 0.0), "scale": Param(float, 1.0)},
          stochastic=True, infer_shape=_sample_infer,
          aliases=("normal", "random_normal"), hint="normal")
def _sample_normal(opctx, attrs, *a):
    shape, dtype = _shape_dtype(attrs)
    return attrs.get("loc", 0.0) + attrs.get("scale", 1.0) * jax.random.normal(
        opctx.rng, shape, dtype)


@register("_sample_gamma", inputs=(),
          params={**_SAMPLE_SPEC, "alpha": Param(float, 1.0), "beta": Param(float, 1.0)},
          stochastic=True, infer_shape=_sample_infer,
          aliases=("random_gamma",), hint="gamma_sample")
def _sample_gamma(opctx, attrs, *a):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.gamma(opctx.rng, attrs.get("alpha", 1.0), shape, dtype) * \
        attrs.get("beta", 1.0)


@register("_sample_exponential", inputs=(),
          params={**_SAMPLE_SPEC, "lam": Param(float, 1.0)},
          stochastic=True, infer_shape=_sample_infer,
          aliases=("random_exponential",), hint="exponential")
def _sample_exponential(opctx, attrs, *a):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.exponential(opctx.rng, shape, dtype) / attrs.get("lam", 1.0)


@register("_sample_poisson", inputs=(),
          params={**_SAMPLE_SPEC, "lam": Param(float, 1.0)},
          stochastic=True, infer_shape=_sample_infer,
          aliases=("random_poisson",), hint="poisson")
def _sample_poisson(opctx, attrs, *a):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.poisson(opctx.rng, attrs.get("lam", 1.0), shape).astype(dtype)

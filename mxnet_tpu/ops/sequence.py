"""Sequence ops: SequenceLast / SequenceMask / SequenceReverse.

Parity surface: /root/reference/src/operator/sequence_last.cc,
sequence_mask.cc, sequence_reverse.cc.  Data is time-major (T, N, ...) as in
the reference; ``use_sequence_length`` gates the per-batch length input.
These are the building blocks of the variable-length story (bucketing,
SURVEY.md §5.7).
"""
from __future__ import annotations

import jax.numpy as jnp

from .param import Param
from .registry import register


def _seq_inputs(attrs):
    if attrs.get("use_sequence_length"):
        return ["data", "sequence_length"]
    return ["data"]


_SEQ_SPEC = {"use_sequence_length": Param(bool, False)}


def _seq_last_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    if attrs.get("use_sequence_length"):
        return [d, (d[1],)], [tuple(d[1:])], []
    return in_shapes, [tuple(d[1:])], []


@register("SequenceLast", inputs=_seq_inputs, params=dict(_SEQ_SPEC),
          infer_shape=_seq_last_infer, no_grad_inputs=("sequence_length",),
          hint="sequencelast")
def _sequence_last(opctx, attrs, data, *rest):
    if not attrs.get("use_sequence_length") or not rest:
        return data[-1]
    seq_len = rest[0].astype(jnp.int32)
    idx = jnp.maximum(seq_len - 1, 0)  # (N,)
    batch = jnp.arange(data.shape[1])
    return data[idx, batch]


@register("SequenceMask", inputs=_seq_inputs,
          params={**_SEQ_SPEC, "value": Param(float, 0.0)},
          no_grad_inputs=("sequence_length",), hint="sequencemask")
def _sequence_mask(opctx, attrs, data, *rest):
    if not attrs.get("use_sequence_length") or not rest:
        return data
    seq_len = rest[0].astype(jnp.int32)
    t = jnp.arange(data.shape[0])
    mask = t[:, None] < seq_len[None, :]  # (T, N)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(attrs.get("value", 0.0), data.dtype))


@register("SequenceReverse", inputs=_seq_inputs, params=dict(_SEQ_SPEC),
          no_grad_inputs=("sequence_length",), hint="sequencereverse")
def _sequence_reverse(opctx, attrs, data, *rest):
    if not attrs.get("use_sequence_length") or not rest:
        return jnp.flip(data, axis=0)
    seq_len = rest[0].astype(jnp.int32)
    T = data.shape[0]
    t = jnp.arange(T)
    # index of the element that lands at position t after per-sequence reversal
    src = jnp.where(t[:, None] < seq_len[None, :],
                    seq_len[None, :] - 1 - t[:, None], t[:, None])  # (T, N)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[src, batch]

"""Shape-manipulation and linear-algebra ops.

Parity surface: /root/reference/src/operator/tensor/matrix_op-inl.h
(Reshape/Flatten/transpose/dot/batch_dot/slice/slice_axis/clip/repeat/tile/
reverse/expand_dims/_slice_assign/_crop_assign_scalar), concat.cc,
slice_channel.cc, pad.cc, swapaxis.cc, crop.cc.  Dots hit the MXU via XLA;
everything else is layout work XLA folds into neighbours.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .param import Param
from .registry import register


# ---------------------------------------------------------------------------
# Reshape family
# ---------------------------------------------------------------------------


def _reshape_target(ishape, target):
    """MXNet Reshape special codes (matrix_op-inl.h ReshapeParam): 0 copy dim,
    -1 infer, -2 copy remaining, -3 merge next two, -4 split (use next two)."""
    out = []
    src = list(ishape)
    i = 0
    t = list(target)
    k = 0
    while k < len(t):
        s = t[k]
        if s == 0:
            out.append(src[i])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == -2:
            out.extend(src[i:])
            i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif s == -4:
            d1, d2 = t[k + 1], t[k + 2]
            k += 2
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2])
            i += 1
        else:
            out.append(s)
            i += 1
        k += 1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(ishape)) if ishape else 1
        out[out.index(-1)] = total // known
    return tuple(int(d) for d in out)


def _reshape_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    target = attrs.get("shape") or attrs.get("target_shape")
    return in_shapes, [_reshape_target(ishape, target)], []


@register("Reshape", aliases=("reshape",),
          params={"shape": Param("shape", ()), "target_shape": Param("shape-or-none", None),
                  "keep_highest": Param(bool, False), "reverse": Param(bool, False)},
          infer_shape=_reshape_infer, hint="reshape")
def _reshape(opctx, attrs, x):
    target = attrs.get("shape") or attrs.get("target_shape")
    return jnp.reshape(x, _reshape_target(x.shape, target))


def _flatten_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    return in_shapes, [(ishape[0], int(np.prod(ishape[1:])) if len(ishape) > 1 else 1)], []


@register("Flatten", aliases=("flatten",), infer_shape=_flatten_infer, hint="flatten")
def _flatten(opctx, attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", params={"axes": Param("shape", ())})
def _transpose(opctx, attrs, x):
    axes = attrs.get("axes") or None
    return jnp.transpose(x, axes)


@register("expand_dims", params={"axis": Param(int, required=True)})
def _expand_dims(opctx, attrs, x):
    return jnp.expand_dims(x, attrs["axis"])


@register("SwapAxis", aliases=("swapaxes", "SwapAxes"),
          params={"dim1": Param(int, 0), "dim2": Param(int, 0)}, hint="swapaxis")
def _swapaxis(opctx, attrs, x):
    return jnp.swapaxes(x, attrs.get("dim1", 0), attrs.get("dim2", 0))


@register("Cast", aliases=("cast",), params={"dtype": Param("dtype", required=True)},
          hint="cast")
def _cast(opctx, attrs, x):
    from .param import _np_dtype

    return x.astype(_np_dtype(attrs["dtype"]))


# ---------------------------------------------------------------------------
# Slicing
# ---------------------------------------------------------------------------


@register("slice", aliases=("crop",),
          params={"begin": Param("shape", required=True), "end": Param("shape", required=True)})
def _slice(opctx, attrs, x):
    begin, end = attrs["begin"], attrs["end"]
    idx = tuple(slice(b, e if e != 0 else None) for b, e in zip(begin, end))
    return x[idx]


@register("slice_axis",
          params={"axis": Param(int, required=True), "begin": Param(int, 0),
                  "end": Param("int-or-none", None)})
def _slice_axis(opctx, attrs, x):
    axis = attrs["axis"] % x.ndim
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(attrs.get("begin", 0), attrs.get("end"))
    return x[tuple(idx)]


@register("_slice_assign", aliases=("_crop_assign",), inputs=("lhs", "rhs"),
          params={"begin": Param("shape", required=True), "end": Param("shape", required=True)})
def _slice_assign(opctx, attrs, lhs, rhs):
    begin, end = attrs["begin"], attrs["end"]
    idx = tuple(slice(b, e if e != 0 else None) for b, e in zip(begin, end))
    return lhs.at[idx].set(rhs)


@register("_crop_assign_scalar",
          params={"begin": Param("shape", required=True), "end": Param("shape", required=True),
                  "scalar": Param(float, 0.0)})
def _crop_assign_scalar(opctx, attrs, x):
    begin, end = attrs["begin"], attrs["end"]
    idx = tuple(slice(b, e if e != 0 else None) for b, e in zip(begin, end))
    return x.at[idx].set(attrs.get("scalar", 0.0))


@register("clip", params={"a_min": Param(float, required=True),
                          "a_max": Param(float, required=True)})
def _clip(opctx, attrs, x):
    return jnp.clip(x, attrs["a_min"], attrs["a_max"])


@register("repeat", params={"repeats": Param(int, required=True),
                            "axis": Param("int-or-none", None)})
def _repeat(opctx, attrs, x):
    return jnp.repeat(x, attrs["repeats"], axis=attrs.get("axis"))


@register("tile", params={"reps": Param("shape", required=True)})
def _tile(opctx, attrs, x):
    return jnp.tile(x, attrs["reps"])


@register("reverse", aliases=("flip",), params={"axis": Param("shape", required=True)})
def _reverse(opctx, attrs, x):
    axis = attrs["axis"]
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(x, axis=axis)


@register("where", inputs=("condition", "x", "y"))
def _where(opctx, attrs, cond, x, y):
    return jnp.where(cond.astype(bool), x, y)


@register("_identity_with_attr_like_rhs", inputs=("lhs", "rhs"),
          no_grad_inputs=("rhs",))
def _identity_like_rhs(opctx, attrs, lhs, rhs):
    return lhs


# ---------------------------------------------------------------------------
# dot / batch_dot — the MXU path (reference: mshadow dot → cuBLAS,
# fully_connected-inl.h:58-59; here jnp.matmul → XLA DotGeneral)
# ---------------------------------------------------------------------------

_DOT_SPEC = {"transpose_a": Param(bool, False), "transpose_b": Param(bool, False)}


def _dot_infer(attrs, in_shapes):
    a, b = in_shapes
    if a is None or b is None:
        return in_shapes, [None], []
    ta, tb = attrs.get("transpose_a", False), attrs.get("transpose_b", False)
    if len(a) == 1 and len(b) == 1:
        return in_shapes, [(1,)], []
    am = a[::-1] if ta else a
    bm = b[::-1] if tb else b
    return in_shapes, [tuple(am[:-1] + bm[1:])], []


@register("dot", inputs=("lhs", "rhs"), params=dict(_DOT_SPEC), infer_shape=_dot_infer)
def _dot(opctx, attrs, a, b):
    if attrs.get("transpose_a", False):
        a = a.T
    if attrs.get("transpose_b", False):
        b = b.T
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b).reshape((1,))
    return jnp.dot(a, b)


def _batch_dot_infer(attrs, in_shapes):
    a, b = in_shapes
    if a is None or b is None:
        return in_shapes, [None], []
    ta, tb = attrs.get("transpose_a", False), attrs.get("transpose_b", False)
    m = a[2] if ta else a[1]
    n = b[1] if tb else b[2]
    return in_shapes, [(a[0], m, n)], []


@register("batch_dot", inputs=("lhs", "rhs"), params=dict(_DOT_SPEC),
          infer_shape=_batch_dot_infer)
def _batch_dot(opctx, attrs, a, b):
    if attrs.get("transpose_a", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# ---------------------------------------------------------------------------
# Concat / SliceChannel / Pad / Crop
# ---------------------------------------------------------------------------


def _concat_infer(attrs, in_shapes):
    dim = attrs.get("dim", 1)
    known = [s for s in in_shapes if s is not None]
    if not known:
        return in_shapes, [None], []
    base = list(known[0])
    total = 0
    for s in in_shapes:
        if s is None:
            return in_shapes, [None], []
        total += s[dim]
    base[dim] = total
    return in_shapes, [tuple(base)], []


@register("Concat", aliases=("concat",), key_var_num_args="num_args",
          params={"num_args": Param(int, required=True), "dim": Param(int, 1)},
          infer_shape=_concat_infer, hint="concat")
def _concat(opctx, attrs, *args):
    return jnp.concatenate(args, axis=attrs.get("dim", 1))


def _slice_channel_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


def _slice_channel_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    n = int(attrs.get("num_outputs", 1))
    if ishape is None:
        return in_shapes, [None] * n, []
    axis = attrs.get("axis", 1) % len(ishape)
    out = list(ishape)
    out[axis] //= n
    if attrs.get("squeeze_axis") and out[axis] == 1:
        del out[axis]
    return in_shapes, [tuple(out)] * n, []


@register("SliceChannel", aliases=("split",),
          params={"num_outputs": Param(int, required=True), "axis": Param(int, 1),
                  "squeeze_axis": Param(bool, False)},
          num_outputs=_slice_channel_outputs, infer_shape=_slice_channel_infer,
          hint="slicechannel")
def _slice_channel(opctx, attrs, x):
    n = int(attrs["num_outputs"])
    axis = attrs.get("axis", 1) % x.ndim
    parts = jnp.split(x, n, axis=axis)
    if attrs.get("squeeze_axis"):
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


def _pad_infer(attrs, in_shapes):
    (ishape,) = in_shapes
    if ishape is None:
        return in_shapes, [None], []
    pw = attrs["pad_width"]
    out = tuple(ishape[i] + pw[2 * i] + pw[2 * i + 1] for i in range(len(ishape)))
    return in_shapes, [out], []


@register("Pad", aliases=("pad",),
          params={"mode": Param(str, "constant", enum=("constant", "edge", "reflect")),
                  "pad_width": Param("shape", required=True),
                  "constant_value": Param(float, 0.0)},
          infer_shape=_pad_infer, hint="pad")
def _pad(opctx, attrs, x):
    pw = attrs["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(x.ndim)]
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=attrs.get("constant_value", 0.0))
    return jnp.pad(x, pairs, mode=mode)


def _crop_inputs(attrs):
    return ["data", "crop_like"] if int(attrs.get("num_args", 1)) == 2 else ["data"]


@register("Crop", inputs=_crop_inputs,
          params={"num_args": Param(int, 1), "offset": Param("shape", (0, 0)),
                  "h_w": Param("shape", (0, 0)), "center_crop": Param(bool, False)},
          no_grad_inputs=("crop_like",), hint="crop")
def _crop_op(opctx, attrs, x, *rest):
    """Spatial crop on NCHW (reference: src/operator/crop.cc)."""
    if rest:
        th, tw = rest[0].shape[2], rest[0].shape[3]
    else:
        th, tw = attrs["h_w"]
    h, w = x.shape[2], x.shape[3]
    if attrs.get("center_crop"):
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = attrs.get("offset", (0, 0))
    return x[:, :, oy:oy + th, ox:ox + tw]

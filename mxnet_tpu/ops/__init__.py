"""Operator library.  Importing this package registers every op family into
the central registry (`mxnet_tpu.ops.registry`), from which the imperative
(`mx.nd`) and symbolic (`mx.sym`) surfaces are generated.

Families mirror /root/reference/src/operator/ (see SURVEY.md §2.2):
elemwise/broadcast/reduce, matrix, indexing, init, sampling, ordering,
nn layers, sequence, optimizer updates, contrib.
"""
from .registry import Op, OpContext, register, get_op, list_ops, registered_ops
from .param import Param
from .pallas_op import register_pallas_op

from . import elemwise  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_ops  # noqa: F401
from . import sample  # noqa: F401
from . import ordering  # noqa: F401
from . import nn  # noqa: F401
from . import sequence  # noqa: F401
from . import rnn_op  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import spatial  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import attention  # noqa: F401
from . import paged  # noqa: F401
from . import ctc  # noqa: F401

__all__ = ["Op", "OpContext", "register", "get_op", "list_ops",
           "registered_ops", "Param", "register_pallas_op"]

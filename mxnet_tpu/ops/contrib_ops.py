"""Contrib ops: FFT/IFFT, CountSketch, and the SSD / Faster-RCNN detection
ops (MultiBoxPrior/Target/Detection, Proposal).

Parity surface: /root/reference/src/operator/contrib/ (fft-inl.h uses cuFFT —
here jnp.fft lowered by XLA; count_sketch-inl.h; multibox_*-inl.h;
proposal-inl.h).  Detection post-processing (matching, NMS) is written with
static shapes + lax.fori_loop so it stays jittable on TPU.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .param import Param
from .registry import register

# ---------------------------------------------------------------------------
# FFT / IFFT — reference pads the last dim to the compute size; output packs
# complex as interleaved (real, imag) pairs doubling the last dim.
# ---------------------------------------------------------------------------


@register("_contrib_fft", params={"compute_size": Param(int, 128)},
          infer_shape=lambda attrs, s: (
              s, [tuple(s[0][:-1]) + (s[0][-1] * 2,)] if s[0] else [None], []),
          hint="fft")
def _fft(opctx, attrs, x):
    out = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
    packed = jnp.stack([out.real, out.imag], axis=-1)
    return packed.reshape(x.shape[:-1] + (x.shape[-1] * 2,)).astype(x.dtype)


@register("_contrib_ifft", params={"compute_size": Param(int, 128)},
          infer_shape=lambda attrs, s: (
              s, [tuple(s[0][:-1]) + (s[0][-1] // 2,)] if s[0] else [None], []),
          hint="ifft")
def _ifft(opctx, attrs, x):
    n = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (n, 2)).astype(jnp.float32)
    cplx = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(cplx, axis=-1)
    # reference ifft returns unnormalized result * n? cuFFT inverse is
    # unnormalized; keep cuFFT semantics (scale by n).
    return (out.real * n).astype(x.dtype)


@register("_contrib_count_sketch", inputs=("data", "h", "s"),
          params={"out_dim": Param(int, required=True),
                  "processing_batch_size": Param(int, 32)},
          no_grad_inputs=("h", "s"),
          infer_shape=lambda attrs, shapes: (
              shapes, [(shapes[0][0], attrs["out_dim"]) if shapes[0] else None], []),
          hint="count_sketch")
def _count_sketch(opctx, attrs, data, h, s):
    """out[n, h[i]] += s[i] * data[n, i] (count_sketch-inl.h)."""
    out_dim = attrs["out_dim"]
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    vals = data * sign[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return out.at[:, idx].add(vals)


# ---------------------------------------------------------------------------
# Box utilities shared by the detection ops
# ---------------------------------------------------------------------------


def _iou(a, b):
    """IoU between corner boxes a (..., 4) and b (..., 4), broadcasting."""
    ix0 = jnp.maximum(a[..., 0], b[..., 0])
    iy0 = jnp.maximum(a[..., 1], b[..., 1])
    ix1 = jnp.minimum(a[..., 2], b[..., 2])
    iy1 = jnp.minimum(a[..., 3], b[..., 3])
    iw = jnp.maximum(ix1 - ix0, 0.0)
    ih = jnp.maximum(iy1 - iy0, 0.0)
    inter = iw * ih
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


# ---------------------------------------------------------------------------
# MultiBoxPrior — anchor generation (multibox_prior-inl.h)
# ---------------------------------------------------------------------------


def _mbp_num_anchors(attrs):
    return len(attrs.get("sizes", (1.0,))) + len(attrs.get("ratios", (1.0,))) - 1


def _mbp_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    na = _mbp_num_anchors(attrs)
    return in_shapes, [(1, d[2] * d[3] * na, 4)], []


@register("_contrib_MultiBoxPrior",
          params={"sizes": Param("float-shape", (1.0,)), "ratios": Param("float-shape", (1.0,)),
                  "clip": Param(bool, False), "steps": Param("float-shape", (-1.0, -1.0)),
                  "offsets": Param("float-shape", (0.5, 0.5))},
          infer_shape=_mbp_infer, no_grad_inputs=("data",), hint="multibox_prior")
def _multibox_prior(opctx, attrs, data):
    sizes = tuple(attrs.get("sizes") or (1.0,))
    ratios = tuple(attrs.get("ratios") or (1.0,))
    offy, offx = tuple(attrs.get("offsets") or (0.5, 0.5))
    h, w = data.shape[2], data.shape[3]
    cy = (jnp.arange(h) + offy) / h
    cx = (jnp.arange(w) + offx) / w
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
    whs = []
    for r in ratios:
        whs.append((sizes[0] * np.sqrt(r) / 2.0, sizes[0] / np.sqrt(r) / 2.0))
    for s in sizes[1:]:
        whs.append((s * np.sqrt(ratios[0]) / 2.0, s / np.sqrt(ratios[0]) / 2.0))
    boxes = []
    for hw, hh in whs:
        boxes.append(jnp.stack([gx - hw, gy - hh, gx + hw, gy + hh], axis=-1))
    out = jnp.stack(boxes, axis=2).reshape(1, -1, 4)  # (1, H*W*A, 4)
    if attrs.get("clip"):
        out = jnp.clip(out, 0.0, 1.0)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# MultiBoxTarget — anchor/GT matching + target encoding (multibox_target-inl.h)
# ---------------------------------------------------------------------------


def _mbt_infer(attrs, in_shapes):
    anchor, label, cls = in_shapes
    if anchor is None or label is None:
        return in_shapes, [None, None, None], []
    a = anchor[1]
    n = label[0]
    return in_shapes, [(n, a * 4), (n, a * 4), (n, a)], []


@register("_contrib_MultiBoxTarget", inputs=("anchor", "label", "cls_pred"),
          params={"overlap_threshold": Param(float, 0.5),
                  "ignore_label": Param(float, -1.0),
                  "negative_mining_ratio": Param(float, -1.0),
                  "negative_mining_thresh": Param(float, 0.5),
                  "minimum_negative_samples": Param(int, 0),
                  "variances": Param("float-shape", (0.1, 0.1, 0.2, 0.2))},
          num_outputs=3, infer_shape=_mbt_infer,
          no_grad_inputs=("anchor", "label", "cls_pred"),
          output_names=lambda attrs: ["loc_target", "loc_mask", "cls_target"],
          hint="multibox_target")
def _multibox_target(opctx, attrs, anchor, label, cls_pred):
    v0, v1, v2, v3 = tuple(attrs.get("variances") or (0.1, 0.1, 0.2, 0.2))
    thresh = attrs.get("overlap_threshold", 0.5)
    anchors = anchor.reshape(-1, 4)  # (A, 4)
    A = anchors.shape[0]

    def per_sample(lbl, pred):
        valid = lbl[:, 0] >= 0  # (O,)
        ious = _iou(anchors[:, None, :], lbl[None, :, 1:5])  # (A, O)
        ious = jnp.where(valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)  # (A,)
        best_iou = jnp.max(ious, axis=1)
        # force-match: the best anchor of each valid gt
        best_anchor = jnp.argmax(ious, axis=0)  # (O,)
        forced = jnp.zeros((A,), bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros((A,), jnp.int32).at[best_anchor].set(
            jnp.arange(lbl.shape[0], dtype=jnp.int32))
        pos = forced | (best_iou >= thresh)
        gt_idx = jnp.where(forced, forced_gt, best_gt)
        gt = lbl[gt_idx]  # (A, 5)
        # encode loc targets with variances
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = gt[:, 3] - gt[:, 1]
        gh = gt[:, 4] - gt[:, 2]
        gcx = (gt[:, 1] + gt[:, 3]) / 2
        gcy = (gt[:, 2] + gt[:, 4]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-12) / v0
        ty = (gcy - acy) / jnp.maximum(ah, 1e-12) / v1
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-12), 1e-12)) / v2
        th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-12), 1e-12)) / v3
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1) * pos[:, None]
        loc_m = jnp.tile(pos[:, None].astype(anchors.dtype), (1, 4))
        cls_t = jnp.where(pos, gt[:, 0] + 1.0, 0.0)
        mining = attrs.get("negative_mining_ratio", -1.0)
        if mining is not None and mining > 0:
            # hard negative mining: rank negatives by max non-background prob
            neg_score = jnp.max(pred[1:, :], axis=0)  # (A,)
            neg_score = jnp.where(pos, -jnp.inf, neg_score)
            num_pos = jnp.sum(pos)
            k = jnp.minimum(
                jnp.maximum((num_pos * mining).astype(jnp.int32),
                            attrs.get("minimum_negative_samples", 0)), A)
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A, dtype=jnp.int32))
            keep_neg = rank < k
            cls_t = jnp.where(pos, cls_t, jnp.where(keep_neg, 0.0, -1.0))
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# MultiBoxDetection — decode + per-class NMS (multibox_detection-inl.h)
# ---------------------------------------------------------------------------


def _nms_suppress(boxes, scores, ids, valid, nms_thresh, force_suppress, topk):
    """Greedy NMS with static shapes: iterate the topk highest-score boxes."""
    A = boxes.shape[0]
    order = jnp.argsort(-scores)
    k = min(topk if topk > 0 else A, A)

    def body(i, keep):
        idx = order[i]
        alive = keep[idx] & valid[idx]
        ious = _iou(boxes[idx][None, :], boxes)  # (A,)
        same_cls = (ids == ids[idx]) | force_suppress
        later = jnp.zeros((A,), bool).at[order[i + 1:]].set(True) if False else None
        del later
        rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A))
        suppress = (ious > nms_thresh) & same_cls & (rank > i)
        return jnp.where(alive & suppress, False, keep)

    keep = jnp.ones((A,), bool)
    keep = lax.fori_loop(0, k, body, keep)
    return keep


def _mbd_infer(attrs, in_shapes):
    cls = in_shapes[0]
    if cls is None:
        return in_shapes, [None], []
    return in_shapes, [(cls[0], cls[2], 6)], []


@register("_contrib_MultiBoxDetection", inputs=("cls_prob", "loc_pred", "anchor"),
          params={"clip": Param(bool, True), "threshold": Param(float, 0.01),
                  "background_id": Param(int, 0), "nms_threshold": Param(float, 0.5),
                  "force_suppress": Param(bool, False),
                  "variances": Param("float-shape", (0.1, 0.1, 0.2, 0.2)),
                  "nms_topk": Param(int, -1)},
          infer_shape=_mbd_infer,
          no_grad_inputs=("cls_prob", "loc_pred", "anchor"),
          hint="multibox_detection")
def _multibox_detection(opctx, attrs, cls_prob, loc_pred, anchor):
    v0, v1, v2, v3 = tuple(attrs.get("variances") or (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def per_sample(probs, locs):
        d = locs.reshape(-1, 4)
        cx = d[:, 0] * v0 * aw + acx
        cy = d[:, 1] * v1 * ah + acy
        w_ = jnp.exp(d[:, 2] * v2) * aw / 2
        h_ = jnp.exp(d[:, 3] * v3) * ah / 2
        boxes = jnp.stack([cx - w_, cy - h_, cx + w_, cy + h_], axis=-1)
        if attrs.get("clip", True):
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = jnp.max(probs[1:, :], axis=0)  # best non-background
        ids = jnp.argmax(probs[1:, :], axis=0).astype(jnp.float32)
        valid = scores > attrs.get("threshold", 0.01)
        keep = _nms_suppress(boxes, scores, ids, valid,
                             attrs.get("nms_threshold", 0.5),
                             bool(attrs.get("force_suppress", False)),
                             int(attrs.get("nms_topk", -1)))
        ok = valid & keep
        out_ids = jnp.where(ok, ids, -1.0)
        return jnp.concatenate([out_ids[:, None], scores[:, None], boxes], axis=-1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# Proposal — RPN proposal generation (proposal-inl.h)
# ---------------------------------------------------------------------------


def _proposal_infer(attrs, in_shapes):
    cls = in_shapes[0]
    if cls is None:
        return in_shapes, [None], []
    n = attrs.get("rpn_post_nms_top_n", 300)
    return in_shapes, [(cls[0] * n, 5)], []


@register("_contrib_Proposal", inputs=("cls_prob", "bbox_pred", "im_info"),
          params={"rpn_pre_nms_top_n": Param(int, 6000),
                  "rpn_post_nms_top_n": Param(int, 300),
                  "threshold": Param(float, 0.7),
                  "rpn_min_size": Param(int, 16),
                  "scales": Param("float-shape", (4, 8, 16, 32)),
                  "ratios": Param("float-shape", (0.5, 1, 2)),
                  "feature_stride": Param(int, 16),
                  "output_score": Param(bool, False),
                  "iou_loss": Param(bool, False)},
          infer_shape=_proposal_infer,
          no_grad_inputs=("cls_prob", "bbox_pred", "im_info"), hint="proposal")
def _proposal(opctx, attrs, cls_prob, bbox_pred, im_info):
    scales = tuple(attrs.get("scales") or (4.0, 8.0, 16.0, 32.0))
    ratios = tuple(attrs.get("ratios") or (0.5, 1.0, 2.0))
    stride = attrs.get("feature_stride", 16)
    n, _, fh, fw = cls_prob.shape
    base = stride
    anchors = []
    for r in ratios:
        for s in scales:
            size = base * base
            ws = np.sqrt(size / r)
            hs = ws * r
            w_, h_ = ws * s, hs * s
            cx = (base - 1) / 2.0
            cy = (base - 1) / 2.0
            anchors.append([cx - (w_ - 1) / 2, cy - (h_ - 1) / 2,
                            cx + (w_ - 1) / 2, cy + (h_ - 1) / 2])
    base_anchors = jnp.asarray(np.array(anchors), cls_prob.dtype)  # (K, 4)
    K = base_anchors.shape[0]
    sy = jnp.arange(fh) * stride
    sx = jnp.arange(fw) * stride
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([gx, gy, gx, gy], axis=-1).reshape(-1, 1, 4)  # (HW,1,4)
    all_anchors = (base_anchors[None, :, :] + shifts).reshape(-1, 4)  # (HW*K,4)
    A = all_anchors.shape[0]
    post_n = int(attrs.get("rpn_post_nms_top_n", 300))

    def per_sample(probs, deltas, info):
        # cls_prob layout (2K, H, W): first K background, last K foreground
        scores = probs[K:, :, :].transpose(1, 2, 0).reshape(-1)
        d = deltas.transpose(1, 2, 0).reshape(-1, 4)
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
        acx = all_anchors[:, 0] + 0.5 * (aw - 1)
        acy = all_anchors[:, 1] + 0.5 * (ah - 1)
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w_ = jnp.exp(d[:, 2]) * aw
        h_ = jnp.exp(d[:, 3]) * ah
        boxes = jnp.stack([cx - 0.5 * (w_ - 1), cy - 0.5 * (h_ - 1),
                           cx + 0.5 * (w_ - 1), cy + 0.5 * (h_ - 1)], axis=-1)
        imh, imw = info[0], info[1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, imw - 1),
                           jnp.clip(boxes[:, 1], 0, imh - 1),
                           jnp.clip(boxes[:, 2], 0, imw - 1),
                           jnp.clip(boxes[:, 3], 0, imh - 1)], axis=-1)
        min_size = attrs.get("rpn_min_size", 16) * info[2]
        keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
                    ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        scores_f = jnp.where(keep_size, scores, -jnp.inf)
        ids = jnp.zeros((A,), jnp.float32)
        keep = _nms_suppress(boxes, scores_f, ids, keep_size,
                             attrs.get("threshold", 0.7), True,
                             int(attrs.get("rpn_pre_nms_top_n", 6000)))
        final = jnp.where(keep, scores_f, -jnp.inf)
        top = jnp.argsort(-final)[:post_n]
        sel = boxes[top]
        return jnp.concatenate([jnp.zeros((post_n, 1), sel.dtype), sel], axis=-1)

    out = jax.vmap(per_sample)(cls_prob, bbox_pred, im_info)
    return out.reshape(-1, 5)

"""Fused RNN operator — TPU-native replacement for the reference's
cuDNN-only ``RNN`` op (src/operator/rnn-inl.h, rnn.cu:10-25).

The reference delegates to cudnnRNNForwardTraining; here the recurrence is a
``lax.scan`` whose per-step work is a single (N, H) x (H, G*H) matmul on the
MXU, while the input projection for the WHOLE sequence is hoisted out of the
scan as one large (T*N, I) x (I, G*H) matmul — the layout XLA tiles best.

Semantics parity with the reference op surface:
  * modes: rnn_relu / rnn_tanh / lstm / gru
  * multi-layer, bidirectional, inter-layer dropout ``p`` (train only)
  * inputs: data (T, N, I) [TNC], parameters (flat vector), state
    (L*D, N, H), and state_cell for LSTM
  * outputs: output (T, N, H*D), plus final state(s) when
    ``state_outputs=True``

Packed parameter layout (documented contract, also used by
``rnn.FusedRNNCell.unpack_weights``): for each layer, for each direction
(forward first): i2h_weight (G*H, in), h2h_weight (G*H, H); then, after all
weights, for each layer/direction: i2h_bias (G*H), h2h_bias (G*H).
Gate order: LSTM [i, f, g, o]; GRU [r, z, n] (linear-before-reset form, the
cuDNN recurrence the reference inherits).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from .param import Param

__all__ = ["rnn_param_size", "rnn_unpack_layout"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _dirs(attrs):
    return 2 if attrs.get("bidirectional") else 1


def rnn_param_size(input_size, state_size, num_layers, mode,
                   bidirectional=False):
    """Total length of the packed parameter vector."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        total += d * (g * h * in_sz + g * h * h + 2 * g * h)
    return total


def rnn_unpack_layout(input_size, state_size, num_layers, mode,
                      bidirectional=False):
    """Yield (layer, direction, kind, offset, shape) for every packed chunk,
    kind in {i2h_weight, h2h_weight, i2h_bias, h2h_bias}."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    out = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        for direction in range(d):
            out.append((layer, direction, "i2h_weight", off, (g * h, in_sz)))
            off += g * h * in_sz
            out.append((layer, direction, "h2h_weight", off, (g * h, h)))
            off += g * h * h
    for layer in range(num_layers):
        for direction in range(d):
            out.append((layer, direction, "i2h_bias", off, (g * h,)))
            off += g * h
            out.append((layer, direction, "h2h_bias", off, (g * h,)))
            off += g * h
    return out


def _slice_params(params, layout):
    """Packed vector -> {(layer, dir): {kind: array}}."""
    table = {}
    for layer, direction, kind, off, shape in layout:
        n = int(np.prod(shape))
        table.setdefault((layer, direction), {})[kind] = \
            lax.dynamic_slice(params, (off,), (n,)).reshape(shape)
    return table


def _cell_step(mode, h):
    """Return f(gates, state) -> (new_state, output) for one time step.
    ``gates`` is the precomputed i2h part; the h2h matmul happens inside."""
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def step(gates_t, state, wh, bh):
            (h_prev,) = state
            nxt = act(gates_t + jnp.dot(h_prev, wh.T) + bh)
            return (nxt,), nxt
        return step
    if mode == "lstm":
        def step(gates_t, state, wh, bh):
            h_prev, c_prev = state
            g = gates_t + jnp.dot(h_prev, wh.T) + bh
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c_prev + \
                jax.nn.sigmoid(i) * jnp.tanh(gg)
            nxt = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (nxt, c), nxt
        return step
    if mode == "gru":
        def step(gates_t, state, wh, bh):
            (h_prev,) = state
            hh = jnp.dot(h_prev, wh.T) + bh           # (N, 3H)
            ir, iz, inn = jnp.split(gates_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(inn + r * hn)                # linear-before-reset
            nxt = (1.0 - z) * n + z * h_prev
            return (nxt,), nxt
        return step
    raise ValueError("unknown RNN mode %r" % mode)


def _run_direction(mode, x, p_tab, h0, c0, reverse):
    """One layer, one direction. x: (T, N, in). Returns (out (T,N,H), hT, cT)."""
    wx, wh = p_tab["i2h_weight"], p_tab["h2h_weight"]
    bx, bh = p_tab["i2h_bias"], p_tab["h2h_bias"]
    t, n, _ = x.shape
    # whole-sequence input projection: one MXU-sized matmul
    gates = (jnp.dot(x.reshape(t * n, -1), wx.T) + bx).reshape(t, n, -1)
    step = _cell_step(mode, h0)
    state0 = (h0, c0) if mode == "lstm" else (h0,)

    def body(state, g_t):
        new_state, out = step(g_t, state, wh, bh)
        return new_state, out

    final, outs = lax.scan(body, state0, gates, reverse=reverse)
    h_t = final[0]
    c_t = final[1] if mode == "lstm" else None
    return outs, h_t, c_t


def _rnn_impl(opctx, attrs, data, params, state, state_cell=None):
    mode = attrs["mode"]
    h = attrs["state_size"]
    nl = attrs["num_layers"]
    d = _dirs(attrs)
    p = attrs.get("p", 0.0)
    t, n, input_size = data.shape
    layout = rnn_unpack_layout(input_size, h, nl, mode, d == 2)
    table = _slice_params(params, layout)

    x = data
    h_finals, c_finals = [], []
    drop_keys = (jax.random.split(opctx.rng, nl - 1)
                 if (opctx.is_train and p > 0.0 and opctx.rng is not None
                     and nl > 1) else None)
    for layer in range(nl):
        outs_dir = []
        for direction in range(d):
            idx = layer * d + direction
            h0 = state[idx]
            c0 = state_cell[idx] if mode == "lstm" else None
            outs, h_t, c_t = _run_direction(
                mode, x, table[(layer, direction)], h0, c0,
                reverse=(direction == 1))
            outs_dir.append(outs)
            h_finals.append(h_t)
            if mode == "lstm":
                c_finals.append(c_t)
        x = outs_dir[0] if d == 1 else jnp.concatenate(outs_dir, axis=-1)
        if drop_keys is not None and layer < nl - 1:
            keep = 1.0 - p
            mask = jax.random.bernoulli(drop_keys[layer], keep, x.shape)
            x = jnp.where(mask, x / keep, jnp.zeros_like(x))

    outputs = [x]
    if attrs.get("state_outputs"):
        outputs.append(jnp.stack(h_finals, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(c_finals, axis=0))
    return tuple(outputs)


def _rnn_inputs(attrs):
    base = ["data", "parameters", "state"]
    if attrs.get("mode") == "lstm":
        base.append("state_cell")
    return base


def _rnn_num_outputs(attrs):
    if not attrs.get("state_outputs"):
        return 1
    return 3 if attrs.get("mode") == "lstm" else 2


def _rnn_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    if dshape is None:
        return in_shapes, [None] * _rnn_num_outputs(attrs), []
    t, n, input_size = dshape
    h = attrs["state_size"]
    nl = attrs["num_layers"]
    d = _dirs(attrs)
    mode = attrs["mode"]
    psize = rnn_param_size(input_size, h, nl, mode, d == 2)
    sshape = (nl * d, n, h)
    args = [tuple(dshape), (psize,), sshape]
    if mode == "lstm":
        args.append(sshape)
    outs = [(t, n, h * d)]
    if attrs.get("state_outputs"):
        outs.append(sshape)
        if mode == "lstm":
            outs.append(sshape)
    return args, outs, []


def _state_zeros_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return in_shapes, [None], []
    batch = d[attrs.get("batch_axis", 0)]
    out = tuple(batch if s == 0 else s for s in attrs["shape"])
    return in_shapes, [out], []


@register("_rnn_state_zeros", inputs=("data",),
          params={"shape": Param("shape", required=True),
                  "batch_axis": Param(int, 0)},
          infer_shape=_state_zeros_infer, hint="rnnstatezeros")
def _rnn_state_zeros(opctx, attrs, data):
    """Zero initial state whose batch dimension is read off a reference
    input at trace time (static under jit).  Replaces the reference's
    ``symbol.zeros(shape=(0, H))`` begin_state idiom — shape-0 deduction
    needs nnvm's consumer->producer inference, which XLA's static-shape
    model deliberately avoids."""
    batch = data.shape[attrs.get("batch_axis", 0)]
    shape = tuple(batch if s == 0 else s for s in attrs["shape"])
    return jnp.zeros(shape, data.dtype)


@register("RNN", inputs=_rnn_inputs, num_outputs=_rnn_num_outputs,
          params={
              "state_size": Param(int, required=True),
              "num_layers": Param(int, required=True),
              "bidirectional": Param(bool, False),
              "mode": Param(str, required=True,
                            enum=("rnn_relu", "rnn_tanh", "lstm", "gru")),
              "p": Param(float, 0.0),
              "state_outputs": Param(bool, False),
          },
          infer_shape=_rnn_infer, stochastic=True, hint="rnn",
          output_names=lambda attrs: (
              ["output"] + (["state"] + (["state_cell"]
               if attrs.get("mode") == "lstm" else [])
               if attrs.get("state_outputs") else [])))
def _rnn(opctx, attrs, data, params, state, *rest):
    state_cell = rest[0] if rest else None
    return _rnn_impl(opctx, attrs, data, params, state, state_cell)

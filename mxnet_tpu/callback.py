"""Training callbacks (parity: /root/reference/python/mxnet/callback.py).

Speedometer is the throughput instrument of every benchmark config —
samples/sec between batch callbacks, the number `BENCH_r*.json` records.
"""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback checkpointing a Module (reference callback.py:10)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving prefix-symbol.json + prefix-%04d.params
    (reference callback.py:39)."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint

            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every ``period`` batches
    (reference callback.py:66)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log samples/sec every ``frequent`` batches (reference callback.py:89).

    Timed with ``time.monotonic()`` (wall-clock steps back under NTP slew;
    a throughput instrument must not).  When telemetry is on, the window's
    data-wait time (from the active StepMonitor) rides along, so a
    starving input pipeline is visible right in the training log.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.last_speed = None
        self.last_data_wait_ms = None
        self._wait_seen_ms = 0.0

    def _data_wait_window_ms(self):
        """Data-wait accumulated since the last report, from the active
        StepMonitor; None when telemetry is off."""
        from . import telemetry as _tm

        if not _tm.enabled():
            return None
        mon = _tm.current_step_monitor()
        if mon is None:
            return None
        total = mon.data_wait_ms_total
        delta = total - self._wait_seen_ms
        self._wait_seen_ms = total
        return max(0.0, delta)

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.monotonic() - self.tic)
                self.last_speed = speed
                wait_ms = self._data_wait_window_ms()
                self.last_data_wait_ms = wait_ms
                wait_sfx = "" if wait_ms is None \
                    else "\tdata-wait=%.1f ms" % wait_ms
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    for name, value in name_value:
                        logging.info(
                            "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t"
                            "Train-%s=%f%s", param.epoch, count, speed, name,
                            value, wait_sfx)
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                        param.epoch, count, speed, wait_sfx)
                self.tic = time.monotonic()
        else:
            self.init = True
            self.tic = time.monotonic()


class ProgressBar:
    """ASCII progress bar over total batches (reference callback.py:131)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")

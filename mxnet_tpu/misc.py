"""Deprecated iteration-based LR schedulers (reference
python/mxnet/misc.py — the pre-``lr_scheduler`` API: ``__call__`` takes
the raw iteration count and scales a stored ``base_lr``). Kept for
parity; new code should use ``mxnet_tpu.lr_scheduler``."""
from __future__ import annotations

import logging
import math


class LearningRateScheduler:
    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """lr = base_lr * factor^(iteration // step)."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError(
                "Schedule step must be greater or equal than 1 round")
        if factor >= 1.0:
            raise ValueError("Factor must be less than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.old_lr = self.base_lr

    def __call__(self, iteration):
        lr = self.base_lr * math.pow(self.factor,
                                     int(iteration / self.step))
        if lr != self.old_lr:
            self.old_lr = lr
            logging.info("At Iteration [%d]: Switch to new learning rate "
                         "%.5f", iteration, lr)
        return lr

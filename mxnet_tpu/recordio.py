"""RecordIO container format — readers/writers bit-compatible with the
reference (dmlc-core recordio + python/mxnet/recordio.py).

Format (dmlc/recordio.h semantics as used by im2rec and ImageRecordIter):
each record = kMagic uint32 (0xced7230a) + lrecord uint32 (upper 3 bits =
continue-flag, lower 29 = length) + payload + padding to 4-byte boundary.
IRHeader packs (flag, label, id, id2) ahead of image payloads
(python/mxnet/recordio.py IRHeader).
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "RecordIOCorruptError"]

_KMAGIC = 0xced7230a
_MAGIC_BYTES = struct.pack("<I", _KMAGIC)
_LFLAG_BITS = 29
_LENGTH_MASK = (1 << _LFLAG_BITS) - 1


class RecordIOCorruptError(IOError):
    """A RecordIO stream is damaged at ``offset`` (truncated trailing
    record from an interrupted writer, bad magic, torn multi-part chain).
    Subclasses IOError, so pre-existing ``except IOError`` handlers keep
    working; the offset lets tooling truncate-and-salvage the prefix."""

    def __init__(self, message, uri, offset):
        super().__init__("%s in %s at byte offset %d"
                         % (message, uri, offset))
        self.uri = uri
        self.offset = offset


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py MXRecordIO,
    backed by dmlc::RecordIOWriter/Reader)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.writable = None
        self.open()

    def open(self):
        from .filesystem import open_uri

        if self.flag == "w":
            self.handle = open_uri(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open_uri(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        self.handle.seek(pos)

    def _write_part(self, cflag, part):
        self.handle.write(struct.pack(
            "<II", _KMAGIC, (cflag << _LFLAG_BITS) | len(part)))
        self.handle.write(part)
        pad = (4 - (len(part) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def write(self, buf):
        """Write one logical record.  dmlc-core multi-part framing: if the
        payload contains the magic word at a 4-byte-aligned offset, split
        there (the magic itself is consumed as the part separator and
        restored on read) with continue-flags 1=first / 2=middle / 3=last."""
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        if len(buf) > _LENGTH_MASK:
            raise ValueError("record too large for RecordIO format")
        # C-speed scan for aligned magic occurrences (bytes.find, not a
        # per-offset Python loop — payloads are ~100KB JPEGs)
        splits = []
        pos = buf.find(_MAGIC_BYTES)
        while pos != -1:
            if pos % 4 == 0:
                splits.append(pos)
                pos = buf.find(_MAGIC_BYTES, pos + 4)
            else:
                pos = buf.find(_MAGIC_BYTES, pos + 1)
        if not splits:
            self._write_part(0, buf)
            return
        begin = 0
        for n, i in enumerate(splits):
            self._write_part(1 if n == 0 else 2, buf[begin:i])
            begin = i + 4
        self._write_part(3, buf[begin:])

    def read(self):
        """Read one logical record, reassembling multi-part continuations
        (continue-flag 1/2/3) with the separator magic restored between
        parts — interchangeable with dmlc-core packs."""
        assert not self.writable
        out = b""
        expect_more = False
        while True:
            rec_off = self.handle.tell()
            head = self.handle.read(8)
            if len(head) < 8:
                if expect_more:
                    raise RecordIOCorruptError(
                        "truncated multi-part record", self.uri, rec_off)
                if head:
                    # a partial header at EOF is a torn trailing record
                    # (writer died mid-append), not a clean end-of-stream —
                    # surface it instead of silently dropping data
                    raise RecordIOCorruptError(
                        "truncated trailing record header (%d of 8 bytes)"
                        % len(head), self.uri, rec_off)
                return None
            magic, lrec = struct.unpack("<II", head)
            if magic != _KMAGIC:
                raise RecordIOCorruptError(
                    "invalid RecordIO magic %#x" % magic, self.uri, rec_off)
            length = lrec & _LENGTH_MASK
            cflag = lrec >> _LFLAG_BITS
            buf = self.handle.read(length)
            if len(buf) < length:
                raise RecordIOCorruptError(
                    "truncated record payload (%d of %d bytes)"
                    % (len(buf), length), self.uri, rec_off)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.handle.read(pad)
            if cflag in (2, 3):
                if not expect_more:
                    raise RecordIOCorruptError(
                        "unexpected continuation record", self.uri, rec_off)
                out += _MAGIC_BYTES + buf
            else:
                if expect_more:
                    raise RecordIOCorruptError(
                        "unterminated multi-part record", self.uri, rec_off)
                out = buf
            if cflag in (0, 3):
                return out
            expect_more = True


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with a sidecar .idx of "key\\tposition" lines
    (reference recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        from .filesystem import open_uri

        super().open()
        self.idx = {}
        self.keys = []
        try:
            if self.writable:
                self.fidx = open_uri(self.idx_path, "w")
            else:
                self.fidx = open_uri(self.idx_path, "r")
                for line in iter(self.fidx.readline, ""):
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        except Exception:
            # a missing/broken sidecar .idx must not leak the record
            # handle opened above (ImageIter's remote-URI fallback probes
            # this path once per construction)
            if self.fidx is not None:
                try:
                    self.fidx.close()
                finally:
                    self.fidx = None
            super().close()
            raise

    def close(self):
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        assert not self.writable
        pos = self.idx[idx]
        self.handle.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into one record string (reference
    recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                    header.id2) + s
    return s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference recordio.py
    unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (HWC uint8) into a record (reference recordio.py
    pack_img; PIL replaces OpenCV)."""
    from .image_backend import encode_image

    buf = encode_image(np.asarray(img, dtype=np.uint8), img_fmt, quality)
    return pack(header, buf)


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image array) (reference recordio.py
    unpack_img)."""
    from .image_backend import decode_image

    header, s = unpack(s)
    channels = 3 if iscolor != 0 else 1
    img = decode_image(s, channels)
    return header, img

"""Internal-op namespace for symbols (reference
python/mxnet/_symbol_internal.py) — see _ndarray_internal.py."""
from . import symbol as _sym


def __getattr__(name):
    if name.startswith("_") and not name.startswith("__") \
            and hasattr(_sym, name):
        return getattr(_sym, name)
    raise AttributeError("no internal Symbol op %r" % name)


def __dir__():
    return [n for n in dir(_sym) if n.startswith("_") and
            not n.startswith("__")]

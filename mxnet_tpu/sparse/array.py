"""RowSparseArray: the 'row_sparse' storage type over a dense logical shape.

Mirrors MXNet's RowSparseNDArray (indices + values rows over shape
(dim0, dim1, ...)): only the rows named in `indices` are materialised,
everything else is implicitly zero.  This is the value type the sparse
parameter plane moves over the wire — an embedding gradient touching 4k
rows of a 10M-row table ships 4k rows, not 10M.

Invariant maintained by the constructor: indices are int64, strictly
increasing (sorted, unique), and values.shape == (len(indices),) +
shape[1:].  Use `row_merge` to reduce duplicate indices by summation
before constructing.
"""
from __future__ import annotations

import numpy as np

__all__ = ["RowSparseArray", "row_merge"]


def row_merge(indices, values):
    """Sum rows that share an index.  Returns (uniq_indices, merged_values)
    with uniq_indices sorted ascending, int64, and merged_values of shape
    (len(uniq),) + values.shape[1:].  O(nnz log nnz) on the host."""
    indices = np.asarray(indices, dtype=np.int64).reshape(-1)
    values = np.asarray(values)
    if values.shape[0] != indices.shape[0]:
        raise ValueError(
            "row_merge: %d indices but %d value rows"
            % (indices.shape[0], values.shape[0]))
    uniq, inverse = np.unique(indices, return_inverse=True)
    if uniq.shape[0] == indices.shape[0]:
        # already unique; np.unique sorted them for us
        order = np.argsort(indices, kind="stable")
        return uniq, np.ascontiguousarray(values[order])
    merged = np.zeros((uniq.shape[0],) + values.shape[1:], dtype=values.dtype)
    np.add.at(merged, inverse, values)
    return uniq, merged


class RowSparseArray(object):
    """indices (nnz,) int64 + values (nnz, ...) rows of a dense logical
    `shape`.  Construction merges duplicate indices by summation so the
    representation is canonical (sorted unique indices)."""

    stype = "row_sparse"

    def __init__(self, indices, values, shape):
        self.shape = tuple(int(s) for s in shape)
        if not self.shape:
            raise ValueError("row_sparse needs a >=1-d logical shape")
        indices, values = row_merge(indices, values)
        if values.shape[1:] != self.shape[1:]:
            raise ValueError(
                "value rows %r do not match logical row shape %r"
                % (values.shape[1:], self.shape[1:]))
        if indices.shape[0] and (indices[0] < 0 or indices[-1] >= self.shape[0]):
            raise IndexError(
                "row index out of bounds for dim0=%d" % self.shape[0])
        self.indices = indices
        self.values = values

    @property
    def nnz(self):
        return int(self.indices.shape[0])

    @property
    def dtype(self):
        return self.values.dtype

    @classmethod
    def from_dense(cls, dense):
        """Keep only rows with any non-zero entry."""
        dense = np.asarray(dense)
        flat = dense.reshape(dense.shape[0], -1)
        idx = np.flatnonzero(np.any(flat != 0, axis=1)).astype(np.int64)
        return cls(idx, dense[idx], dense.shape)

    def to_dense(self, out=None):
        if out is None:
            out = np.zeros(self.shape, dtype=self.values.dtype)
        else:
            out[:] = 0
        out[self.indices] = self.values
        return out

    def __add__(self, other):
        if not isinstance(other, RowSparseArray):
            return NotImplemented
        if other.shape != self.shape:
            raise ValueError("shape mismatch %r vs %r"
                             % (self.shape, other.shape))
        return RowSparseArray(
            np.concatenate([self.indices, other.indices]),
            np.concatenate([self.values, other.values]), self.shape)

    def __repr__(self):
        return "RowSparseArray(nnz=%d, shape=%r, dtype=%s)" % (
            self.nnz, self.shape, self.values.dtype)

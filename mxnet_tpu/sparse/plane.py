"""Worker-side sparse parameter plane (docs/how_to/sparse.md).

``SparseParamPlane`` routes row-sparse traffic to the sharded embedding
tables on the kvstore servers: rows are owned by server
``row_id % num_servers`` (every worker and server agree on that function,
so there is no directory service), pulls gather the touched rows across
shards concurrently, and pushes ride the comm engine's per-key FIFO
chains so they pipeline and coalesce exactly like dense gradient pushes
— a pull for a key always observes every push for that key submitted
before it.

The worker never holds a full table: per step it moves O(touched rows)
bytes, and the optimizer state never leaves the servers.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..base import register_env
from .array import row_merge

register_env("MXNET_KVSTORE_SPARSE_COALESCE", 1, int,
             "Coalesce multi-slot row-sparse pushes into one fused "
             "envelope per server (one idempotency token per server per "
             "step); 0 sends one RPC per (slot, server).")
register_env("MXNET_KVSTORE_SPARSE_CAPACITY", 2048, int,
             "Default worker-side row capacity for a row_sparse embedding "
             "slot: the bound executor holds at most this many touched "
             "rows per batch instead of the full table.")

__all__ = ["SparseParamPlane", "default_capacity"]


def default_capacity():
    return int(os.environ.get("MXNET_KVSTORE_SPARSE_CAPACITY", "2048"))


def _unwrap(kv):
    """Accept an AsyncKVStore (engine + dist store), a bare
    DistAsyncKVStore, or a plain list of ServerClient."""
    engine = None
    if isinstance(kv, (list, tuple)):
        return list(kv), 0, None
    inner = getattr(kv, "inner", kv)
    engine = getattr(kv, "_engine", None)
    clients = getattr(inner, "_clients", None)
    if clients is None:
        raise ValueError(
            "sparse plane needs a dist kvstore (ServerClient transport); "
            "got %r" % (type(kv).__name__,))
    return list(clients), int(getattr(inner, "rank", 0)), engine


class SparseParamPlane(object):
    def __init__(self, kv_or_clients, rank=None):
        self._clients, kv_rank, self._engine = _unwrap(kv_or_clients)
        self.rank = kv_rank if rank is None else int(rank)
        self.num_servers = len(self._clients)
        self._pool = None
        self._pool_lock = threading.Lock()
        self._metas = {}
        # bench/acceptance instrumentation: bytes moved by the last
        # pull/push and the peak single-transfer size — the worker-side
        # resident footprint of the sparse plane
        self.last_pull_bytes = 0
        self.peak_transfer_bytes = 0

    # -- helpers ------------------------------------------------------------
    def _map(self, fn, items):
        items = list(items)
        if len(items) <= 1:
            return [fn(it) for it in items]
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.num_servers,
                    thread_name_prefix="sparse-plane")
        return list(self._pool.map(fn, items))

    def _note(self, nbytes):
        if nbytes > self.peak_transfer_bytes:
            self.peak_transfer_bytes = nbytes

    def _wait_key(self, key):
        if self._engine is not None:
            self._engine.wait([("sparse", key)])

    # -- control plane ------------------------------------------------------
    def init_table(self, key, num_rows, row_shape, dtype="float32",
                   init=("zeros",)):
        """Declare a sharded table on every server.  Idempotent."""
        if np.isscalar(row_shape):
            row_shape = (int(row_shape),)
        meta = {"num_rows": int(num_rows), "row_shape": tuple(row_shape),
                "dtype": str(dtype), "init": tuple(init),
                "num_servers": self.num_servers}
        self._metas[key] = meta

        def one(i):
            m = dict(meta)
            m["server_index"] = i
            self._clients[i].init_table(key, m)

        self._map(one, range(self.num_servers))
        return meta

    def set_sparse_optimizer(self, updater, is_recovery=False):
        self._map(lambda c: c.set_sparse_optimizer(updater, is_recovery),
                  self._clients)

    def table_info(self):
        """Merged per-server audit: [{key: info}, ...] indexed by server."""
        return self._map(lambda c: c.table_info(), self._clients)

    # -- data plane ---------------------------------------------------------
    def pull_rows(self, key, row_ids, out=None):
        """Gather rows by id across shards, returned in input order.
        Waits the key's engine chain first so the pull observes every
        previously submitted push for that key."""
        self._wait_key(key)
        ids = np.asarray(row_ids, dtype=np.int64).reshape(-1)
        ns = self.num_servers
        if ns == 1:
            block = self._clients[0].pull_rows(key, ids)
            got = np.asarray(block)
        else:
            owner = ids % ns
            shards = [np.flatnonzero(owner == s) for s in range(ns)]
            parts = self._map(
                lambda s: (self._clients[s].pull_rows(key, ids[shards[s]])
                           if shards[s].size else None),
                range(ns))
            first = next(p for p in parts if p is not None)
            got = np.empty((ids.shape[0],) + first.shape[1:],
                           dtype=first.dtype)
            for s, p in enumerate(parts):
                if p is not None:
                    got[shards[s]] = p
        self.last_pull_bytes = got.nbytes
        self._note(got.nbytes)
        if out is not None:
            out[:got.shape[0]] = got
            return out
        return got

    def _shard(self, ids, vals):
        """Merge duplicates then split by owning server; yields
        (server, ids, vals) for non-empty shards."""
        ids, vals = row_merge(ids, vals)
        ns = self.num_servers
        if ns == 1:
            yield 0, ids, vals
            return
        owner = ids % ns
        for s in range(ns):
            sel = np.flatnonzero(owner == s)
            if sel.size:
                yield s, ids[sel], vals[sel]

    def push_rows(self, key, row_ids, values, priority=0):
        """Push a row-sparse gradient: worker-side duplicate merge, then
        one push_rows per owning server.  With an engine the push is
        submitted asynchronously under the key's FIFO chain (pipelining
        with compute, like dense pushes); without one it is synchronous."""
        ids = np.asarray(row_ids, dtype=np.int64).reshape(-1)
        vals = np.asarray(values)
        self._note(vals.nbytes)

        def do_push():
            self._map(lambda part: self._clients[part[0]].push_rows(
                key, part[1], part[2], rank=self.rank),
                self._shard(ids, vals))

        if self._engine is None:
            do_push()
        else:
            self._engine.submit(do_push, [("sparse", key)],
                                priority=priority,
                                label="push_rows:%s" % (key,))

    def push_rows_multi(self, triples, priority=0):
        """Coalesced multi-slot push: all (key, ids, vals) triples fuse
        into ONE ``multi`` envelope per server — one idempotency token
        per server per step, so crash-replay applies the whole step's
        sparse traffic exactly once per server.  Falls back to per-key
        pushes when MXNET_KVSTORE_SPARSE_COALESCE=0."""
        triples = [(k, np.asarray(i, dtype=np.int64).reshape(-1),
                    np.asarray(v)) for k, i, v in triples]
        if not triples:
            return
        if os.environ.get("MXNET_KVSTORE_SPARSE_COALESCE", "1") == "0":
            for k, i, v in triples:
                self.push_rows(k, i, v, priority=priority)
            return
        per_server = {}
        for key, ids, vals in triples:
            self._note(vals.nbytes)
            for s, sids, svals in self._shard(ids, vals):
                per_server.setdefault(s, []).append(
                    ("push_rows", key, sids, svals, self.rank))

        def do_push():
            self._map(lambda item: self._clients[item[0]].multi(item[1]),
                      per_server.items())

        keys = [("sparse", k) for k, _i, _v in triples]
        if self._engine is None:
            do_push()
        else:
            self._engine.submit(do_push, keys, priority=priority,
                                label="push_rows_multi:%d" % len(triples))

    def wait(self, key=None):
        """Barrier over sparse traffic: one key's chain, or everything."""
        if self._engine is None:
            return
        if key is not None:
            self._engine.wait([("sparse", key)])
        else:
            self._engine.wait([("sparse", k) for k in self._metas])

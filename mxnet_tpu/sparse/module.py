"""SparseEmbeddingModule — Module with per-slot ``stype='row_sparse'``
embedding params routed through the sparse parameter plane.

The jax autodiff path cannot emit a sparse-shaped cotangent (a vjp's
output must match the primal's dense shape), so the sparse routing is
*structural* instead: each row_sparse slot's Embedding weight is bound at
shape ``(capacity, dim)`` — capacity = the max distinct rows one batch
can touch, NOT the vocabulary.  Per batch the module

1. uniquifies the slot's raw ids and remaps them to local positions
   ``[0, n_uniq)`` (np.unique's inverse),
2. pulls only the touched rows from the server shards into the bound
   weight buffer (zero-padded to capacity),
3. runs the normal forward/backward — the weight gradient is the
   ``(capacity, dim)`` buffer, O(touched) not O(vocab),
4. pushes ``grad[:n_uniq]`` back under the original row ids (coalesced
   across slots into one fused envelope per server), where the
   server-placed optimizer applies it.

Dense params keep the stock Module path untouched.  The full table never
exists on the worker: resident bytes are O(capacity), the logical table
can be arbitrarily larger than device memory (docs/how_to/sparse.md).
"""
from __future__ import annotations

import copy
import logging

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError
from ..module.module import Module
from .plane import SparseParamPlane, default_capacity
from .updaters import from_dense_optimizer

__all__ = ["SparseEmbeddingModule"]


class SparseEmbeddingModule(Module):
    """``sparse_slots`` maps a slot name to its routing config::

        {"slot0": {"data": "slot0_indices",   # index input (a data name)
                   "weight": "slot0_weight",  # the Embedding weight param
                   "num_rows": 10_000_000,    # logical vocabulary
                   "capacity": 4096,          # bound rows (optional)
                   "init": ("uniform", 0.01)  # server row init (optional)
                   }}

    The symbol must bind each slot's Embedding with
    ``input_dim=capacity`` (see models/dlrm.py:get_dlrm, which builds the
    symbol and this config together)."""

    def __init__(self, symbol, sparse_slots, **kwargs):
        super().__init__(symbol, **kwargs)
        self._slots = {}
        for name, cfg in dict(sparse_slots).items():
            slot = {"name": name, "data": cfg["data"],
                    "weight": cfg["weight"],
                    "num_rows": int(cfg["num_rows"]),
                    "capacity": int(cfg.get("capacity",
                                            default_capacity())),
                    "init": tuple(cfg.get("init", ("uniform", 0.01))),
                    "uniq": None}
            if slot["weight"] not in self._param_names:
                raise MXNetError("row_sparse slot %r: weight %r is not a "
                                 "parameter of the symbol"
                                 % (name, slot["weight"]))
            if slot["data"] not in self._data_names:
                raise MXNetError("row_sparse slot %r: data %r is not a "
                                 "data input" % (name, slot["data"]))
            self._slots[name] = slot
        self._plane = None

    # -- routing hooks ------------------------------------------------------
    def _sparse_param_indices(self):
        weights = {s["weight"] for s in self._slots.values()}
        return tuple(i for i, n in enumerate(self._param_names)
                     if n in weights)

    def _decide_fused(self):
        # the per-batch id remap + row pull/push is inherently eager
        return False

    @property
    def sparse_plane(self):
        return self._plane

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="dist_async", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        super().init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        kv = self._kvstore
        if kv is None or "dist" not in kv.type:
            raise MXNetError(
                "SparseEmbeddingModule needs a dist kvstore: the sharded "
                "embedding tables live on the parameter servers")
        if hasattr(kv, "sparse_plane"):
            self._plane = kv.sparse_plane()  # comm-engine FIFO + pipelining
        else:
            self._plane = SparseParamPlane(kv)
        for slot in self._slots.values():
            i = self._param_names.index(slot["weight"])
            cap, dim = self._exec_group.param_arrays[i].shape
            if cap != slot["capacity"]:
                raise MXNetError(
                    "slot %r: symbol binds weight rows %d but capacity "
                    "is %d — build the symbol with input_dim=capacity"
                    % (slot["name"], cap, slot["capacity"]))
            slot["param_index"] = i
            slot["dim"] = int(dim)
            slot["data_index"] = self._exec_group.data_names.index(
                slot["data"])
            slot["key"] = slot["weight"]
            self._plane.init_table(slot["key"], num_rows=slot["num_rows"],
                                   row_shape=(dim,), init=slot["init"])
        # server-placed optimizer: same hyperparameters (incl. the
        # 1/batch rescale) as the dense slots, state never leaves the
        # servers
        self._plane.set_sparse_optimizer(
            from_dense_optimizer(self._optimizer))

    # -- per-batch routing --------------------------------------------------
    def _route_sparse(self, data_batch):
        """Uniquify/remap each slot's ids, pull the touched rows into the
        bound weight buffers, and return a shallow-copied batch whose
        index inputs hold local positions."""
        if self._plane is None or not self._slots:
            return data_batch
        batch = copy.copy(data_batch)
        data = list(batch.data)
        for slot in self._slots.values():
            di = slot["data_index"]
            raw = data[di]
            ids_np = (raw.asnumpy() if isinstance(raw, nd.NDArray)
                      else np.asarray(raw))
            ids = ids_np.astype(np.int64)
            uniq, inverse = np.unique(ids.reshape(-1), return_inverse=True)
            if uniq.size > slot["capacity"]:
                raise MXNetError(
                    "slot %r: batch touches %d distinct rows > capacity "
                    "%d — raise the slot capacity (or "
                    "MXNET_KVSTORE_SPARSE_CAPACITY)"
                    % (slot["name"], uniq.size, slot["capacity"]))
            rows = self._plane.pull_rows(slot["key"], uniq)
            buf = np.zeros((slot["capacity"], slot["dim"]),
                           dtype=rows.dtype)
            buf[:uniq.size] = rows
            self._exec_group.param_arrays[slot["param_index"]]._set(buf)
            data[di] = nd.array(
                inverse.reshape(ids.shape).astype(ids_np.dtype))
            slot["uniq"] = uniq
        batch.data = data
        return batch

    def forward(self, data_batch, is_train=None):
        super().forward(self._route_sparse(data_batch), is_train)

    def forward_backward(self, data_batch):
        super().forward_backward(self._route_sparse(data_batch))

    def update(self):
        """Push each slot's touched-row gradient to the servers (one
        coalesced envelope per server), then run the stock dense update
        with the sparse grads masked out of the kvstore loop."""
        eg = self._exec_group
        pending = []
        if self._plane is not None:
            for slot in self._slots.values():
                uniq = slot.get("uniq")
                if uniq is None or "param_index" not in slot:
                    continue
                g = eg.grad_arrays[slot["param_index"]]
                if g is None:
                    continue
                grad = g.asnumpy()
                pending.append((slot["key"], uniq, grad[:uniq.size]))
                slot["uniq"] = None
            if pending:
                self._plane.push_rows_multi(pending)
        saved = {}
        for slot in self._slots.values():
            i = slot.get("param_index")
            if i is not None and eg.grad_arrays[i] is not None:
                saved[i] = eg.grad_arrays[i]
                eg.grad_arrays[i] = None
        try:
            super().update()
        finally:
            for i, g in saved.items():
                eg.grad_arrays[i] = g

    # -- observability ------------------------------------------------------
    def sparse_stats(self):
        """Worker-side plane counters for bench/acceptance: per-slot
        resident bytes (the bound capacity buffers), logical table bytes,
        and the plane's transfer peaks."""
        out = {"slots": {}, "plane": None}
        for slot in self._slots.values():
            dim = slot.get("dim")
            if dim is None:
                continue
            itemsize = 4  # float32 tables
            out["slots"][slot["name"]] = {
                "resident_bytes": slot["capacity"] * dim * itemsize,
                "logical_bytes": slot["num_rows"] * dim * itemsize,
                "capacity": slot["capacity"],
                "num_rows": slot["num_rows"],
            }
        if self._plane is not None:
            out["plane"] = {
                "peak_transfer_bytes": self._plane.peak_transfer_bytes,
                "last_pull_bytes": self._plane.last_pull_bytes,
                "num_servers": self._plane.num_servers,
            }
        return out

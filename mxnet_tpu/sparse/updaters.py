"""Server-placed row-wise optimizers for the sparse parameter plane.

These run *inside* KVStoreServer: the worker ships touched-row gradients
(push_rows) and the server applies the update lazily per row, keeping the
optimizer state (e.g. AdaGrad accumulators) server-side — ZeRO-style
memory relief for workers, which never hold the full table or any
optimizer state.

Everything here must be picklable: the updater travels over the wire
(set_sparse_optimizer) and is journaled verbatim into the server
snapshot, so crash-restart resumes with bit-identical state.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SparseSGD", "SparseAdaGrad", "get_sparse_updater"]


class _SparseOptimizer(object):
    """Base: vectorized over the batch of touched rows of one push."""

    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0):
        self.lr = float(learning_rate)
        self.wd = float(wd)
        self.rescale_grad = float(rescale_grad)

    def state_shape(self, row_shape):
        """Shape of the per-row state block, or None for stateless."""
        return None

    def update_rows(self, weight_rows, grad_rows, state_rows):
        """In-place update of weight_rows (nnz, dim); state_rows is the
        matching (nnz,)+state_shape block or None.  Must mutate both in
        place so the server's row store sees the result."""
        raise NotImplementedError


class SparseSGD(_SparseOptimizer):
    """w -= lr * (rescale_grad * g + wd * w); optional momentum keeps a
    per-row velocity on the server."""

    def __init__(self, learning_rate=0.01, wd=0.0, momentum=0.0,
                 rescale_grad=1.0):
        super(SparseSGD, self).__init__(learning_rate, wd, rescale_grad)
        self.momentum = float(momentum)

    def state_shape(self, row_shape):
        return tuple(row_shape) if self.momentum else None

    def update_rows(self, weight_rows, grad_rows, state_rows):
        g = grad_rows * self.rescale_grad
        if self.wd:
            g = g + self.wd * weight_rows
        if self.momentum:
            state_rows *= self.momentum
            state_rows -= self.lr * g
            weight_rows += state_rows
        else:
            weight_rows -= self.lr * g


class SparseAdaGrad(_SparseOptimizer):
    """Per-row AdaGrad: h += g^2; w -= lr * g / (sqrt(h) + eps).  The
    accumulator h lives on the server beside the row."""

    def __init__(self, learning_rate=0.01, wd=0.0, eps=1e-7,
                 rescale_grad=1.0):
        super(SparseAdaGrad, self).__init__(learning_rate, wd, rescale_grad)
        self.eps = float(eps)

    def state_shape(self, row_shape):
        return tuple(row_shape)

    def update_rows(self, weight_rows, grad_rows, state_rows):
        g = grad_rows * self.rescale_grad
        if self.wd:
            g = g + self.wd * weight_rows
        state_rows += g * g
        weight_rows -= self.lr * g / (np.sqrt(state_rows) + self.eps)


_REGISTRY = {"sgd": SparseSGD, "adagrad": SparseAdaGrad}


def get_sparse_updater(name, **kwargs):
    """Factory: get_sparse_updater('adagrad', learning_rate=0.1)."""
    try:
        cls = _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError("unknown sparse optimizer %r (have: %s)"
                         % (name, sorted(_REGISTRY)))
    return cls(**kwargs)


def from_dense_optimizer(opt):
    """Map a worker-side mxnet_tpu.optimizer.Optimizer onto its
    server-placed sparse twin, preserving lr/wd/rescale_grad so sparse and
    dense slots train under identical hyperparameters."""
    kind = type(opt).__name__.lower()
    lr = getattr(opt, "lr", 0.01)
    wd = getattr(opt, "wd", 0.0)
    rescale = getattr(opt, "rescale_grad", 1.0)
    if kind == "adagrad":
        return SparseAdaGrad(learning_rate=lr, wd=wd, rescale_grad=rescale)
    momentum = getattr(opt, "momentum", 0.0) if kind == "sgd" else 0.0
    return SparseSGD(learning_rate=lr, wd=wd, momentum=momentum,
                     rescale_grad=rescale)

"""Sparse parameter plane: row-sparse values, sharded embedding tables on
the kvstore servers, and server-placed optimizers (docs/how_to/sparse.md).

Import discipline: this package is imported by ``kvstore_server`` (for
``row_merge``) *during* the mxnet_tpu package import, so the eager
surface here must stay numpy-only.  The plane and module layers — which
pull in kvstore/comm_engine/module — load lazily on first attribute
access.
"""
from .array import RowSparseArray, row_merge  # noqa: F401
from .updaters import (SparseAdaGrad, SparseSGD,  # noqa: F401
                       get_sparse_updater)

__all__ = ["RowSparseArray", "row_merge", "SparseSGD", "SparseAdaGrad",
           "get_sparse_updater", "SparseParamPlane",
           "SparseEmbeddingModule"]

_LAZY = {
    "SparseParamPlane": ("mxnet_tpu.sparse.plane", "SparseParamPlane"),
    "plane": ("mxnet_tpu.sparse.plane", None),
    "SparseEmbeddingModule": ("mxnet_tpu.sparse.module",
                              "SparseEmbeddingModule"),
    "module": ("mxnet_tpu.sparse.module", None),
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError("module %r has no attribute %r"
                             % (__name__, name))
    import importlib

    mod = importlib.import_module(target[0])
    obj = mod if target[1] is None else getattr(mod, target[1])
    globals()[name] = obj
    return obj

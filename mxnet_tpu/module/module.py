"""Module — the primary training API over (symbol, data, label).

Parity: /root/reference/python/mxnet/module/module.py:323-566.  Binding
builds a mesh-wide DataParallelExecutorGroup (one jitted executor, batch
sharded over contexts) instead of per-device executors; update() keeps both
reference paths — centralized kvstore update and replicated local updater.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, compute_dtype=None, dist_mesh=None):
        super().__init__(logger=logger)
        # dist_mesh: None (auto) spans the executor mesh over every process
        # when running under jax.distributed — the TPU-native dist_sync data
        # plane; False forces a process-local module (e.g. a per-worker
        # oracle/eval model inside a distributed job)
        self._dist_mesh = dist_mesh
        # TPU-native mixed precision: compute in bf16, keep f32 master
        # params/grads/optimizer state (no reference equivalent — the
        # reference casts the symbol to fp16 instead)
        self._compute_dtype = compute_dtype
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        assert len(work_load_list) == len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()
        _check_input_names(symbol, self._data_names, "data", True)
        _check_input_names(symbol, self._label_names, "label", False)
        _check_input_names(symbol, self._state_names, "state", True)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param", True)

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused_ok = False
        self._fused_pending = None
        self._tm_mon = None  # telemetry.StepMonitor, created when enabled

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a Module from a saved checkpoint (reference module.py:86)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Save symbol + params (+ optimizer states) (reference
        module.py:106)."""
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        logging.info("Saved checkpoint to \"%s\"", param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info("Saved optimizer state to \"%s\"", state_name)

    # ------------------------------------------------------------------
    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.get_outputs()
        return list(zip(self._output_names, [o.shape for o in outs])) \
            if outs else []

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        """Initialize parameters (reference module.py:237)."""
        if self.params_initialized and not force_init:
            logging.warning(
                "Parameters already initialized and force_init=False. "
                "init_params call ignored.")
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None and not (arg_params and aux_params):
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                name: nd.zeros(arr.shape, dtype=arr.dtype)
                for name, arr in zip(self._exec_group.param_names,
                                     self._exec_group.param_arrays)}
        if self._aux_params is None:
            self._aux_params = {
                name: nd.zeros(arr.shape, dtype=arr.dtype)
                for name, arr in zip(self._exec_group.aux_names,
                                     self._exec_group.aux_arrays)}

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    if tuple(cache_arr.shape) != tuple(arr.shape):
                        raise MXNetError(
                            "shape mismatch for %s: loaded %s vs expected %s"
                            % (name, cache_arr.shape, arr.shape))
                    arr[:] = cache_arr
            else:
                if not allow_missing and cache is not None:
                    raise RuntimeError(
                        "%s is not presented in the provided arg_params" % name)
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name)), arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            logging.warning(
                "Parameters already initialized and force_init=False. "
                "set_params call ignored.")
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write", mesh=None, partition_rules=None):
        """Bind executors (reference module.py:323).

        ``mesh`` / ``partition_rules`` opt into GSPMD sharding: a named
        device mesh (``jax.sharding.Mesh``, a ``sharding.MeshConfig``, or
        the string form ``"data=-1,model=2"``) plus regex partition rules
        (a ``sharding.PartitionRules``, a preset name, or a raw
        ``[(regex, PartitionSpec), ...]`` list).  The batch shards on the
        leading mesh axis; parameters follow their matching rule; the
        fused train step lowers once under the resulting shardings.  With
        neither given, ``MXNET_SHARDING_MESH`` / ``MXNET_SHARDING_RULES``
        activate a layout from the environment; with nothing set the
        replicated data-parallel path is unchanged."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        assert not (for_training is False and inputs_need_grad)

        self._data_shapes = self._exec_group_descs(data_shapes)
        self._label_shapes = self._exec_group_descs(label_shapes) \
            if label_shapes else None

        shared_group = None
        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        if mesh is None and partition_rules is None:
            from ..base import env

            env_mesh = env("MXNET_SHARDING_MESH", "", str)
            env_rules = env("MXNET_SHARDING_RULES", "", str)
            if env_mesh:
                mesh = env_mesh
            if env_rules:
                partition_rules = env_rules

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group, self.logger,
            self._fixed_param_names, grad_req, state_names=self._state_names,
            compute_dtype=self._compute_dtype, dist_mesh=self._dist_mesh,
            mesh=mesh, partition_rules=partition_rules)
        self._total_exec_bytes = 0
        if _telemetry.enabled() and self._exec_group._mesh is not None:
            self._telemetry_monitor().note_mesh(self._exec_group._mesh)

        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

        if shared_module is not None and shared_module.optimizer_initialized:
            self.borrow_optimizer(shared_module)

    @staticmethod
    def _exec_group_descs(shapes):
        from ..io import DataDesc

        out = []
        for s in shapes:
            out.append(s if isinstance(s, DataDesc) else DataDesc(s[0], s[1]))
        return out

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = self._exec_group_descs(data_shapes)
        self._label_shapes = self._exec_group_descs(label_shapes) \
            if label_shapes else None
        self._exec_group.reshape(self._data_shapes, self._label_shapes)

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Install optimizer + kvstore (reference module.py:432)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        if self._exec_group._multiprocess:
            if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
                # global-mesh sync DP: the gradient all-reduce is compiled
                # into the (fused) step over the multi-process mesh, and
                # every worker applies the identical update to its replica —
                # the kvstore degrades to a control-plane facade (init
                # broadcast, barrier, rank), replacing the reference's
                # server-side merge (kvstore_dist_server.h:164-200)
                update_on_kvstore = False
            elif kvstore and "dist" in kvstore.type:
                # dist_async needs each worker's OWN gradient at the server;
                # the mesh has already summed them — the two data planes
                # cannot compose
                raise MXNetError(
                    "dist_async requires per-worker gradients: construct "
                    "the Module with dist_mesh=False to train process-local "
                    "replicas against the parameter server")

        if kvstore and update_on_kvstore:
            # centralized-update path: ride the async comm engine so
            # push/pull overlap compute (MXNET_KVSTORE_ASYNC=0 restores
            # the synchronous loop; no-op if already wrapped)
            from ..comm_engine import maybe_async

            kvstore = maybe_async(kvstore)

        batch_size = self._exec_group.batch_size
        if self._exec_group._multiprocess:
            # gradients are summed over the GLOBAL batch by the compiled
            # psum regardless of kvstore type, so the default grad scale
            # must account for every process's shard
            import jax

            batch_size *= jax.process_count()
        elif kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            # one mesh executor regardless of len(context): updater indices
            # are plain param positions (the reference's per-device
            # i*ndev+k scheme only applies to its one-executor-per-device
            # layout, executor_group.py:77)
            idx2name = dict(enumerate(self._exec_group.param_names))
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size/"
                    "num_workers (%s vs. %s). Is this intended?",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore,
                                skip_indices=self._sparse_param_indices())
            if not update_on_kvstore and "dist" in kvstore.type and \
                    self._exec_group._multiprocess:
                # pull the rank-0-broadcast init back so every replica
                # starts identical (reference inits from rank 0 only,
                # kvstore_dist.h:64-82); afterwards the kvstore data plane
                # is out of the training loop
                for idx, name in enumerate(self._param_names):
                    kvstore.pull(idx, self._arg_params[name], priority=-idx)
                self._exec_group.set_params(self._arg_params,
                                            self._aux_params)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        if kvstore and "dist" in kvstore.type and \
                os.environ.get("MXNET_KVSTORE_ELASTIC", "0") == "1":
            # elastic preemption path (fault_tolerance.md §elasticity):
            # SIGTERM drains in-flight comm ops, checkpoints if the user
            # registered save hooks, leaves the membership table, and
            # exits clean so launch.py counts a preemption, not a crash
            from ..kvstore import install_preemption_handler

            install_preemption_handler(kvstore)

        self.optimizer_initialized = True
        self._fused_ok = self._decide_fused()

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _sparse_param_indices(self):
        """Param indices routed around the dense kvstore path entirely.
        The base Module has none; SparseEmbeddingModule returns its
        row_sparse slots, whose tables live sharded on the servers and
        must never be init'd (or pushed) as dense tensors."""
        return ()

    def _decide_fused(self):
        """Whether update() can run as ONE jitted fwd+bwd+optimizer program
        (Executor.fused_step).  Requires the replicated-updater path (no
        server-side aggregation), an optimizer with a traceable update rule,
        plain grad_req='write', and no monitor hook (which needs eager
        internals).  MXNET_FUSED_STEP=0 is the escape hatch back to the
        reference-style eager per-key loop."""
        from ..base import env

        if env("MXNET_FUSED_STEP", "1", str) == "0":
            return False
        from .. import faults as _faults
        if _faults.targets_corruption("guardian.grad"):
            # scheduled gradient corruption (nan/bitflip fault injection)
            # rewrites host-visible grad buffers; the fused step never
            # materializes them, so fall back to the eager loop
            return False
        if self._update_on_kvstore or self._updater is None:
            return False
        if self._kvstore is not None and "dist" in self._kvstore.type \
                and not self._exec_group._multiprocess:
            # single-process dist (degenerate 1-worker run): keep the eager
            # kvstore loop; with a real multi-process mesh the fused step
            # carries the compiled psum and the kvstore is a facade
            return False
        if not type(self._optimizer).has_pure_update():
            return False
        if any(self._exec_group.grad_req.get(n) == "add"
               for n in self._param_names):
            return False
        if self.inputs_need_grad:  # fused step differentiates params only
            return False
        if self._exec_group._monitor_callback is not None:
            return False
        return True

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def _wait_async_comm(self):
        """Drain deferred kvstore traffic before parameters are read.
        update() leaves pushes/pulls in flight on an async kvstore so
        they overlap the next batch's host-side prep; the executor reads
        raw param buffers (no NDArray read guard fires), so the overlap
        window closes here."""
        kv = getattr(self, "_kvstore", None)
        if kv is not None and getattr(self, "_update_on_kvstore", False):
            wait_all = getattr(kv, "wait_all", None)
            if wait_all is not None:
                wait_all()

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        # run any deferred fused batch first so its grads/outputs are not
        # interleaved with (or clobbered by) this forward
        self._flush_fused_pending()
        self._wait_async_comm()
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._flush_fused_pending()
        self._exec_group.backward(out_grads=out_grads)

    def _telemetry_monitor(self):
        """Per-module StepMonitor, created on first use; callers must gate
        on ``telemetry.enabled()`` so the off path allocates nothing."""
        from .. import telemetry as _tm

        if self._tm_mon is None:
            self._tm_mon = _tm.StepMonitor(_tm)
        return self._tm_mon

    def forward_backward(self, data_batch):
        """Fused forward+backward — one XLA program per batch.  When the
        fully-fused step is enabled, execution is deferred to update() so
        forward, backward, AND the optimizer run as a single donated XLA
        program (see _decide_fused)."""
        assert self.binded and self.params_initialized
        if _telemetry.enabled():
            mon = self._telemetry_monitor()
            mon.step_begin()
            mon.note_batch(data_batch)  # recompile fingerprint
        if self._fused_ok and self.optimizer_initialized:
            self._fused_pending = data_batch
            return
        # this path does NOT go through self.forward(), so the async
        # overlap window from the previous update() closes here
        self._wait_async_comm()
        self._exec_group.forward_backward(data_batch)

    def _flush_fused_pending(self):
        """A caller wants grads/outputs before update(): fall back to the
        two-phase path for this batch."""
        if self._fused_pending is not None:
            batch, self._fused_pending = self._fused_pending, None
            self._exec_group.forward_backward(batch)

    def update(self):
        """Apply the optimizer to every parameter (reference module.py:553).
        On the fused path this runs the whole pending train step as one
        compiled program; otherwise the reference's eager per-key
        push/pull/updater loop."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._guardian_action = "ok"
        if self._fused_pending is not None:
            batch, self._fused_pending = self._fused_pending, None
            self._exec_group.fused_step(batch, self._optimizer, self._updater)
            g = getattr(self, "_guardian", None)
            if g is not None and self._exec_group.execs:
                # the on-device guard already gated the poisoned update out
                # with a where(); this read lands where the step syncs
                # anyway (metric update) and only feeds the response ladder
                verdict = getattr(self._exec_group.execs[0],
                                  "_guard_verdict", None)
                if verdict is not None:
                    ok, gnorm = verdict
                    self._guardian_action = g.observe(finite=bool(ok),
                                                      gnorm=float(gnorm))
            if _telemetry.enabled():
                self._telemetry_step_end()
            return
        from .. import faults as _faults
        if _faults.targets_corruption("guardian.grad"):
            self._corrupt_grads()
        if self._update_on_kvstore:
            # pushes go out in backward order (the order grads become
            # available) with priority=-index; the wait is deferred so an
            # async kvstore overlaps comms with metric/update + the next
            # batch fetch — forward() closes the window
            _update_params_on_kvstore(
                self._exec_group.param_arrays,
                self._exec_group.grad_arrays,
                self._kvstore,
                param_order=self._exec_group.backward_param_order(),
                defer_wait=True)
        else:
            # on a multi-process mesh the gradients coming out of the
            # executor are already globally summed (the psum is compiled
            # into the backward), so the kvstore must NOT reduce them again
            kv = self._kvstore
            if kv is not None and self._exec_group._multiprocess:
                kv = None
            if self._guardian_observe_eager() != "ok":
                # anomalous batch: leave params/updater state untouched —
                # the eager-path equivalent of the fused guard's where()
                if _telemetry.enabled():
                    self._telemetry_step_end()
                return
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=1,
                           kvstore=kv)
        if _telemetry.enabled():
            self._telemetry_step_end()

    def _each_grad(self):
        for arr in self._exec_group.grad_arrays:
            for a in (arr if isinstance(arr, list) else [arr]):
                if a is not None:
                    yield a

    def _corrupt_grads(self):
        """Run every host-visible gradient past the fault plan's corrupt
        hook (nan/bitflip kinds on the ``guardian.grad`` op); an armed rule
        rewrites the chosen element in place.  Only reached when a plan
        actually targets corruption (update() pre-checks), so the normal
        path never pays the host transfer."""
        from .. import faults as _faults

        for a in self._each_grad():
            before = a.asnumpy()
            after = _faults.corrupt("guardian.grad", before)
            if after is not before:
                a[:] = after

    def _guardian_observe_eager(self):
        """Host-side guard for the eager update path: finiteness + global
        grad-norm over every gradient, fed to the guardian's response
        ladder.  Returns the action ("ok" = apply this batch)."""
        g = getattr(self, "_guardian", None)
        if g is None:
            return "ok"
        finite = True
        # accumulate the norm in f32, matching the fused guard: a
        # finite-but-huge corruption (exponent bit-flip ~1e38) overflows
        # the square-sum and reads as non-finite right here, with no
        # spike history needed
        sq = np.float32(0)
        with np.errstate(over="ignore"):  # overflow IS the signal
            for a in self._each_grad():
                v = np.asarray(a.asnumpy(), dtype=np.float32)
                if not np.all(np.isfinite(v)):
                    finite = False
                    break
                sq += np.sum(np.square(v))
        gnorm = float(np.sqrt(sq)) if finite else float("inf")
        self._guardian_action = g.observe(finite=finite, gnorm=gnorm)
        return self._guardian_action

    def _telemetry_step_end(self):
        """Close the step span: batch size, wall time, and — on the fused
        path's compile misses — one XLA cost analysis for MFU."""
        mon = self._telemetry_monitor()
        ex = self._exec_group.execs[0] if self._exec_group.execs else None
        if ex is not None and getattr(ex, "_fused_new_compile", False):
            ex._fused_new_compile = False
            mon.note_compile(ex)
        mon.step_end(getattr(self._exec_group, "batch_size", 0))

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        self._flush_fused_pending()
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        self._flush_fused_pending()
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._flush_fused_pending()
        self._exec_group.update_metric(eval_metric, labels)

    # ------------------------------------------------------------------
    def _sync_params_from_devices(self):
        self._wait_async_comm()
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updater.set_states(fin.read())

    def install_monitor(self, mon):
        assert self.binded
        self._fused_ok = False  # monitor needs eager per-tensor internals
        self._flush_fused_pending()
        self._exec_group.install_monitor(mon)

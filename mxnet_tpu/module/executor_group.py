"""DataParallelExecutorGroup — the data-parallel engine of the frontend.

TPU-native redesign of /root/reference/python/mxnet/module/executor_group.py:77.
The reference binds ONE executor per device, slices the batch in Python
(`decide_slices` :207, `_load_data` :43), and reduces gradients through
KVStore/Comm.  Here there is ONE executor jitted over a `jax.sharding.Mesh`
of all given contexts: the batch is sharded on the mesh's 'data' axis, the
parameters are replicated, and XLA's SPMD partitioner inserts the gradient
all-reduce (the Comm/KVStore reduce compiled into the step — ICI collectives
instead of PCIe/host staging).  `workload` (work_load_list) is accepted for
API parity but even splits are the only mesh-friendly layout, so uneven
splits are rejected rather than silently ignored.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from .. import context as ctx_mod
from .. import ndarray as nd
from ..executor import Executor
from ..io import DataDesc

__all__ = ["DataParallelExecutorGroup"]


def _merge_shape(desc, batch_size):
    return (batch_size,) + tuple(desc.shape[1:])


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None, compute_dtype=None,
                 dist_mesh=None, mesh=None, partition_rules=None):
        self.symbol = symbol
        self.contexts = contexts
        self.compute_dtype = compute_dtype
        if workload and len(set(workload)) > 1:
            raise MXNetError(
                "work_load_list with uneven splits is unsupported on a device "
                "mesh: SPMD sharding requires equal shards per device")
        self.param_names = list(param_names)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = list(fixed_param_names or [])
        self.state_names = list(state_names or [])
        self.logger = logger
        self._monitor_callback = None

        if grad_req != "null" and for_training:
            self.grad_req = {}
            for k in self.arg_names:
                if k in self.param_names:
                    self.grad_req[k] = ("null" if k in self.fixed_param_names
                                        else grad_req)
                elif k in [d.name if isinstance(d, DataDesc) else d[0]
                           for d in data_shapes]:
                    self.grad_req[k] = grad_req if inputs_need_grad else "null"
                else:
                    self.grad_req[k] = "null"
        else:
            self.grad_req = {k: "null" for k in self.arg_names}

        self._mesh = None
        self._data_sharding = None
        self._repl_sharding = None
        self._multiprocess = False
        self._rules = None        # PartitionRules (GSPMD rule path)
        self._param_specs = None  # resolved {name: PartitionSpec} at bind
        self._data_axis = "data"
        import jax

        if mesh is not None or partition_rules is not None:
            # GSPMD rule path: an explicit named mesh (possibly multi-axis,
            # e.g. ("data", "model")) + regex partition rules.  The batch
            # shards on the LEADING axis; parameters follow their rule's
            # PartitionSpec, resolved at bind once shapes are inferred.
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            from .. import sharding as _sharding

            self._rules = _sharding.as_rules(
                partition_rules if partition_rules is not None
                else "replicated")
            if not isinstance(mesh, Mesh):
                mesh = _sharding.build_mesh(mesh if mesh is not None
                                            else "data=-1")
            self._mesh = mesh
            self._data_axis = mesh.axis_names[0]
            self._multiprocess = jax.process_count() > 1
            self._data_sharding = NamedSharding(mesh, P(self._data_axis))
            self._repl_sharding = NamedSharding(mesh, P())
        elif jax.process_count() > 1 and dist_mesh is not False:
            # multi-host data parallelism: ONE global mesh over every device
            # of every process; the fused step compiles the gradient psum
            # over it (TPU-native replacement for the reference's
            # ps-lite push/pull, src/kvstore/kvstore_dist.h:183-230 — the
            # collective rides ICI/DCN inside the step instead of a host
            # round-trip per key)
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            self._multiprocess = True
            self._mesh = Mesh(np.asarray(jax.devices()), ("data",))
            self._data_sharding = NamedSharding(self._mesh, P("data"))
            self._repl_sharding = NamedSharding(self._mesh, P())
        elif len(contexts) > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            devices = [c.jax_device() for c in contexts]
            self._mesh = Mesh(np.array(devices), ("data",))
            self._data_sharding = NamedSharding(self._mesh, P("data"))
            self._repl_sharding = NamedSharding(self._mesh, P())

        self.batch_size = None
        self.slices = None
        self.execs: List[Executor] = []
        self.data_arrays = None
        self.label_arrays = None
        self.param_arrays = None
        self.grad_arrays = None
        self.aux_arrays = None
        self.input_grad_arrays = None
        self.data_shapes = None
        self.label_shapes = None
        self.data_names = None
        self.label_names = None
        self.data_layouts = None
        self.label_layouts = None
        self.output_layouts = None
        self.num_outputs = None
        self.bind_exec(data_shapes, label_shapes, shared_group)

    # ------------------------------------------------------------------
    def decide_slices(self, data_shapes):
        """Batch → per-device slices (reference executor_group.py:207).  On
        the mesh the split is implicit in the sharding; slices are kept for
        API parity (e.g. Monitor output naming)."""
        assert len(data_shapes) > 0
        major_axis = [DataDesc.get_batch_axis(getattr(s, "layout", "NCHW"))
                      for s in data_shapes]
        for (name, shape), axis in zip(
                [(getattr(s, "name", s[0]), getattr(s, "shape", None) or s[1])
                 for s in data_shapes], major_axis):
            if axis == -1:
                continue
            batch_size = shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size, \
                    "all data must have the same batch size"
            else:
                self.batch_size = batch_size
                if self._rules is not None:
                    # explicit mesh: the batch splits over the leading
                    # ('data') axis only — a ("data","model") 4x2 mesh
                    # shards the batch 4 ways
                    import jax

                    n = int(self._mesh.shape[self._data_axis])
                    if self._multiprocess:
                        # per-process batch; each process feeds its shard
                        n = max(1, n // jax.process_count())
                elif self._multiprocess:
                    import jax

                    # per-process batch; each process feeds its local devices
                    n = jax.local_device_count()
                else:
                    n = len(self.contexts)
                if batch_size % n != 0:
                    raise MXNetError(
                        "batch size %d is not divisible by the %d-way 'data' "
                        "split of the mesh" % (batch_size, n))
                step = batch_size // n
                self.slices = [slice(i * step, (i + 1) * step)
                               for i in range(n)]
        return major_axis

    def _as_desc(self, shapes):
        out = []
        for s in shapes or []:
            if isinstance(s, DataDesc):
                out.append(s)
            else:
                out.append(DataDesc(s[0], s[1]))
        return out

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        """Bind the single mesh executor (reference binds one per device via
        _bind_ith_exec :538)."""
        self.data_shapes = self._as_desc(data_shapes)
        self.label_shapes = self._as_desc(label_shapes) if label_shapes else []
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [l.name for l in self.label_shapes]
        self.data_layouts = self.decide_slices(self.data_shapes)
        if self.label_shapes:
            self.label_layouts = self.decide_slices(self.label_shapes)

        input_shapes = {d.name: d.shape for d in self.data_shapes}
        input_shapes.update({l.name: l.shape for l in self.label_shapes})
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**input_shapes)
        if arg_shapes is None:
            raise MXNetError("shape inference failed at bind")

        input_types = {d.name: getattr(d, "dtype", np.float32)
                       for d in self.data_shapes + self.label_shapes}
        arg_types, _, aux_types = self.symbol.infer_type(**input_types)

        shared_exec = shared_group.execs[0] if shared_group else None
        ctx0 = self.contexts[0]
        shared_pool = shared_exec.arg_dict if shared_exec else {}

        args = {}
        grads = {}
        for name, shape, dtype in zip(self.arg_names, arg_shapes, arg_types):
            if shared_exec is not None and name in self.param_names and \
                    name in shared_pool:
                args[name] = shared_pool[name]  # bucketing shares param memory
            else:
                args[name] = nd.zeros(shape, ctx0, dtype=dtype)
            if self.grad_req.get(name, "null") != "null":
                grads[name] = nd.zeros(shape, ctx0, dtype=dtype)
        aux = {}
        shared_aux = shared_exec.aux_dict if shared_exec else {}
        for name, shape, dtype in zip(self.aux_names, aux_shapes, aux_types):
            if name in shared_aux and \
                    tuple(shared_aux[name].shape) == tuple(shape):
                aux[name] = shared_aux[name]
            else:
                aux[name] = nd.zeros(shape, ctx0, dtype=dtype)

        executor = Executor(self.symbol, ctx0, args, grads or None,
                            self.grad_req, aux, shared_exec=shared_exec,
                            compute_dtype=self.compute_dtype,
                            cast_exclude=self.label_names)
        self.execs = [executor]
        if self._rules is not None:
            self._apply_rule_shardings(
                executor,
                {n: tuple(s) for n, s in zip(self.arg_names, arg_shapes)},
                {n: tuple(s) for n, s in zip(self.aux_names, aux_shapes)})
        elif self._mesh is not None:
            self._apply_shardings(executor)

        # parity views: param_arrays/grad_arrays are lists over "devices";
        # with one mesh executor each entry is the single (sharded) array.
        self.param_arrays = [executor.arg_dict[name]
                             for name in self.param_names]
        self.grad_arrays = [executor.grad_dict.get(name)
                            for name in self.param_names]
        self.aux_arrays = [executor.aux_dict[name] for name in self.aux_names]
        self.data_arrays = [executor.arg_dict[name] for name in self.data_names]
        self.label_arrays = [executor.arg_dict[name]
                             for name in self.label_names]
        self.input_grad_arrays = [executor.grad_dict.get(name)
                                  for name in self.data_names] \
            if self.inputs_need_grad else []
        self.num_outputs = len(self.symbol.list_outputs())
        if self._monitor_callback is not None:
            executor.set_monitor_callback(self._monitor_callback)

    def backward_param_order(self):
        """Parameter indices in the order their gradients become available
        — last layer first.  ``param_names`` follows the symbol's
        topological (forward) order, so the reverse approximates backward
        completion order; the centralized update path issues kvstore
        pushes in this order so late-layer gradients hit the wire while
        early layers are conceptually still being produced (reference
        kvstore priority scheduling, kvstore_dist.h + engine)."""
        return list(range(len(self.param_names) - 1, -1, -1))

    def _replicate(self, x):
        """Place a process-local array as fully-replicated on the (possibly
        multi-process) mesh.  Arrays already equivalently placed pass
        through untouched — so ``set_params`` with pre-sharded arrays (a
        checkpoint restored onto the mesh) is a placement no-op instead of
        a spurious copy or a cross-process error."""
        from ..sharding import place

        return place(x, self._mesh, self._repl_sharding.spec)

    def _apply_rule_shardings(self, executor, arg_shapes, aux_shapes):
        """Resolve the regex rules against the inferred shapes and hand the
        whole layout to ``Executor.set_shardings``: batch inputs shard on
        the leading mesh axis, every other arg/aux gets its rule's
        PartitionSpec.  From here on every write path (set_params, batch
        loads, the fused step's in_shardings) follows the same specs."""
        from jax.sharding import PartitionSpec as P

        from .. import sharding as _sharding
        from ..base import env

        batch_names = set(self.data_names) | set(self.label_names)
        ruled = {name: shape
                 for name, shape in list(arg_shapes.items())
                 + list(aux_shapes.items()) if name not in batch_names}
        specs = self._rules.match(ruled)
        if env("MXNET_SHARDING_VALIDATE", 1, int):
            _sharding.validate_specs(self._mesh, specs, ruled)
        if env("MXNET_SHARDING_EXPLAIN", 0, int):
            self.logger.info(
                "partition rules (%s) on mesh %s:\n%s", self._rules.name,
                _sharding.mesh_axes(self._mesh),
                self._rules.explain_str(ruled))
        self._param_specs = specs
        all_specs = dict(specs)
        for name in batch_names:
            all_specs[name] = P(self._data_axis)
        executor.set_shardings(self._mesh, all_specs)
        self._note_shard_bytes(executor)

    def _note_shard_bytes(self, executor):
        """Telemetry gauge pair making a layout's memory win a number:
        actual average per-device parameter residency vs the fully
        replicated baseline."""
        from .. import telemetry

        if not telemetry.enabled():
            return
        from .. import sharding as _sharding

        arrays = [executor.arg_dict[n] for n in self.param_names]
        arrays += [executor.aux_dict[n] for n in self.aux_names]
        per_dev, repl = _sharding.param_bytes(arrays)
        telemetry.gauge(
            "mxtpu_params_sharded_bytes",
            "Average per-device parameter+aux bytes under the active "
            "sharding").set(per_dev)
        telemetry.gauge(
            "mxtpu_params_replicated_bytes",
            "Per-device parameter+aux bytes if fully replicated").set(repl)

    def _apply_shardings(self, executor):
        """Replicate params, shard batch inputs on the 'data' axis.  XLA's
        partitioner then emits the psum for gradient aggregation (the
        compiled equivalent of Comm reduce, comm.h:120-360)."""
        import jax

        batch_names = set(self.data_names) | set(self.label_names)
        for name, arr in executor.arg_dict.items():
            if name in batch_names:
                # batch entries are re-placed per step by _load_batch; on a
                # multi-process mesh the bound placeholder stays local (its
                # global shape differs from the bound local shape)
                if not self._multiprocess:
                    arr._set(jax.device_put(arr._data, self._data_sharding))
            else:
                arr._set(self._replicate(arr._data))
        for arr in executor.aux_dict.values():
            arr._set(self._replicate(arr._data))
        for arr in executor.grad_dict.values():
            arr._set(self._replicate(arr._data))

    def reshape(self, data_shapes, label_shapes):
        if data_shapes == self.data_shapes and \
                label_shapes == self.label_shapes:
            return
        # preserve trained parameter/aux memory across the rebind (the
        # reference reshapes executors in place, executor_group.py:378)
        old_exec = self.execs[0] if self.execs else None
        self.batch_size = None
        self.bind_exec(data_shapes, label_shapes, reshape=True)
        if old_exec is not None:
            new_exec = self.execs[0]
            for name in self.param_names:
                if name in old_exec.arg_dict:
                    new_exec.arg_dict[name]._set(old_exec.arg_dict[name]._data)
            for name in self.aux_names:
                if name in old_exec.aux_dict:
                    new_exec.aux_dict[name]._set(old_exec.aux_dict[name]._data)

    # ------------------------------------------------------------------
    def set_params(self, arg_params, aux_params):
        for executor in self.execs:
            executor.copy_params_from(arg_params, aux_params)
        if self._rules is not None:
            # copy_params_from routes through Executor._write_arg, which
            # commits each value straight onto the mesh under its spec
            # (pre-sharded arrays pass through) — nothing left to place
            return
        if self._mesh is not None:
            self._apply_shardings(self.execs[0])

    def get_params(self, arg_params, aux_params):
        """Copy current params into the given dicts (reference
        executor_group.get_params — the weighted merge across devices is a
        no-op here: the mesh keeps one replicated copy)."""
        if self._rules is not None:
            # tensor-parallel layouts: gather shards to host values first
            # (cross-process arrays are not directly indexable)
            from .. import sharding as _sharding

            executor = self.execs[0]
            for name in self.param_names:
                arg_params[name][:] = _sharding.gather_params(
                    {name: executor.arg_dict[name]})[name]
            for name in self.aux_names:
                aux_params[name][:] = _sharding.gather_params(
                    {name: executor.aux_dict[name]})[name]
            return
        for name in self.param_names:
            arg_params[name][:] = self.execs[0].arg_dict[name]
        for name in self.aux_names:
            aux_params[name][:] = self.execs[0].aux_dict[name]

    # ------------------------------------------------------------------
    def _load_batch(self, data_batch):
        """Place batch data onto the mesh (scatter ≈ _load_data :43)."""
        import jax

        executor = self.execs[0]
        arrays = list(zip(self.data_names, data_batch.data))
        if self.label_names and getattr(data_batch, "label", None):
            arrays += list(zip(self.label_names, data_batch.label))
        expected = {d.name: tuple(d.shape)
                    for d in self.data_shapes + self.label_shapes}
        for name, src in arrays:
            dst = executor.arg_dict[name]
            if self._multiprocess:
                # every process contributes its local batch as one shard of
                # the GLOBAL batch (global batch = num_processes x local
                # batch, split on the mesh 'data' axis); the traced step
                # then runs SPMD over all hosts with the gradient psum
                # compiled in.  Host numpy feeds the global array directly —
                # no staging device round trip for numpy-backed iterators.
                host = src.asnumpy() if isinstance(src, nd.NDArray) \
                    else np.asarray(src)
                if tuple(host.shape) != expected[name]:
                    raise MXNetError(
                        "batch shape %s for %s does not match bound shape %s"
                        % (tuple(host.shape), name, expected[name]))
                if host.dtype != dst.dtype:
                    host = host.astype(dst.dtype)
                data = jax.make_array_from_process_local_data(
                    self._data_sharding, host)
            else:
                data = src._data if isinstance(src, nd.NDArray) else \
                    nd.array(src)._data
                if tuple(data.shape) != expected[name]:
                    raise MXNetError(
                        "batch shape %s for %s does not match bound shape %s"
                        % (tuple(data.shape), name, expected[name]))
                if data.dtype != dst.dtype:
                    data = data.astype(dst.dtype)
                if self._data_sharding is not None:
                    data = jax.device_put(data, self._data_sharding)
            dst._set(data)

    def forward(self, data_batch, is_train=None):
        self._load_batch(data_batch)
        if is_train is None:
            is_train = self.for_training
        self.execs[0].forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True to run backward"
        self.execs[0].backward(out_grads)

    def forward_backward(self, data_batch):
        """Fused fwd+bwd in one XLA program — the TPU hot path."""
        self._load_batch(data_batch)
        self.execs[0].forward_backward()

    def fused_step(self, data_batch, optimizer, updater):
        """Fully-fused train step: fwd+bwd+optimizer update as ONE donated
        XLA program (Executor.fused_step) — replaces forward_backward +
        the per-key kvstore push/pull loop of the reference hot path."""
        self._load_batch(data_batch)
        self.execs[0].fused_step(optimizer, updater, self.param_names)

    def _local_view(self, arr):
        """Process-local slice of a batch-sharded global output (each worker
        sees the rows it contributed — matching the reference, where a
        worker's executor outputs cover only its own batch)."""
        if not self._multiprocess:
            return arr
        import jax.numpy as jnp

        x = arr._data
        if getattr(x, "is_fully_addressable", True):
            return arr
        shards = sorted(x.addressable_shards, key=lambda s: s.index[0].start
                        if s.index and s.index[0].start is not None else 0)
        seen = set()
        parts = []
        for s in shards:
            key = tuple((d.start, d.stop) for d in s.index if d is not None)
            if key in seen:  # replicated output: one copy is enough
                continue
            seen.add(key)
            parts.append(s.data)
        local = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        return nd.NDArray(local, self.contexts[0])

    def get_outputs(self, merge_multi_context=True):
        return [self._local_view(o) for o in self.execs[0].outputs]

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return [self._local_view(g) if g is not None else None
                for g in (self.execs[0].grad_dict.get(name)
                          for name in self.data_names)]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        self._monitor_callback = mon.stat_helper if hasattr(mon, "stat_helper") \
            else mon
        for executor in self.execs:
            executor.set_monitor_callback(self._monitor_callback)
